"""Reference model-format interop: protobuf ``__model__`` + tensor streams.

The reference serializes ProgramDesc as a protobuf message
(/root/reference/paddle/fluid/framework/framework.proto:211) and loads
it in inference via LoadModel (/root/reference/paddle/fluid/inference/
io.cc). This module implements the WIRE format directly — a minimal
hand-written proto2 codec driven by field tables transcribed from the
schema — so a reference-saved model dir loads into a paddle_tpu Program
(and vice versa) without a protobuf dependency. JSON stays the native
format (io.py); this is the compatibility path.

Tensor data uses the reference's stream framing
(framework/lod_tensor.cc:219 SerializeToStream + tensor_util.cc:383
TensorToStream): u32 version, u64 lod_level, per-level u64 byte-size +
u64 offsets, then u32 version, i32 TensorDesc proto size, TensorDesc,
raw bytes. ``load_combine`` files are these streams concatenated in
sorted-name order (inference/io.cc:111 sorts the param list).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# proto2 wire primitives
# ---------------------------------------------------------------------------

_WT_VARINT, _WT_64BIT, _WT_LEN, _WT_32BIT = 0, 1, 2, 5


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out: bytearray, val: int) -> None:
    if val < 0:
        val &= (1 << 64) - 1  # negative int32/64 → 10-byte varint
    while True:
        b = val & 0x7F
        val >>= 7
        if val:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _iter_fields(data: bytes):
    """Yield (field_number, wire_type, payload). payload is an int for
    varint/fixed, bytes for length-delimited."""
    pos = 0
    n = len(data)
    while pos < n:
        key, pos = _read_varint(data, pos)
        fno, wt = key >> 3, key & 7
        if wt == _WT_VARINT:
            val, pos = _read_varint(data, pos)
        elif wt == _WT_LEN:
            ln, pos = _read_varint(data, pos)
            val = data[pos:pos + ln]
            pos += ln
        elif wt == _WT_32BIT:
            val = struct.unpack("<I", data[pos:pos + 4])[0]
            pos += 4
        elif wt == _WT_64BIT:
            val = struct.unpack("<Q", data[pos:pos + 8])[0]
            pos += 8
        else:
            raise ValueError("unsupported wire type %d (field %d)"
                             % (wt, fno))
        yield fno, wt, val


def _to_signed(val: int, bits: int = 64) -> int:
    if val >= 1 << (bits - 1):
        val -= 1 << bits
    return val


# ---------------------------------------------------------------------------
# framework.proto field tables (framework.proto:42-216)
# kind: int / bool / float / str / enum / msg:<table> ; '*' = repeated
# ---------------------------------------------------------------------------

TENSOR_DESC = {1: ("data_type", "enum"), 2: ("dims", "int*")}
LOD_TENSOR_DESC = {1: ("tensor", "msg", TENSOR_DESC),
                   2: ("lod_level", "int")}
VAR_TYPE = {
    1: ("type", "enum"),
    2: ("selected_rows", "msg", TENSOR_DESC),
    3: ("lod_tensor", "msg", LOD_TENSOR_DESC),
    4: ("tensor_array", "msg", LOD_TENSOR_DESC),
}
VAR_DESC = {1: ("name", "str"), 2: ("type", "msg", VAR_TYPE),
            3: ("persistable", "bool"), 4: ("need_check_feed", "bool")}
OP_DESC_VAR = {1: ("parameter", "str"), 2: ("arguments", "str*")}
OP_DESC_ATTR = {
    1: ("name", "str"), 2: ("type", "enum"),
    3: ("i", "int"), 4: ("f", "float"), 5: ("s", "str"),
    6: ("ints", "int*"), 7: ("floats", "float*"), 8: ("strings", "str*"),
    10: ("b", "bool"), 11: ("bools", "bool*"), 12: ("block_idx", "int"),
    13: ("l", "int"), 14: ("blocks_idx", "int*"), 15: ("longs", "int*"),
}
OP_DESC = {
    1: ("inputs", "msg*", OP_DESC_VAR), 2: ("outputs", "msg*", OP_DESC_VAR),
    3: ("type", "str"), 4: ("attrs", "msg*", OP_DESC_ATTR),
    5: ("is_target", "bool"),
}
BLOCK_DESC = {
    1: ("idx", "int"), 2: ("parent_idx", "int"),
    3: ("vars", "msg*", VAR_DESC), 4: ("ops", "msg*", OP_DESC),
    5: ("forward_block_idx", "int"),
}
VERSION = {1: ("version", "int")}
PROGRAM_DESC = {1: ("blocks", "msg*", BLOCK_DESC),
                4: ("version", "msg", VERSION)}

# AttrType enum (framework.proto:25)
(ATTR_INT, ATTR_FLOAT, ATTR_STRING, ATTR_INTS, ATTR_FLOATS, ATTR_STRINGS,
 ATTR_BOOLEAN, ATTR_BOOLEANS, ATTR_BLOCK, ATTR_LONG, ATTR_BLOCKS,
 ATTR_LONGS) = range(12)


def decode_message(data: bytes, table: Dict) -> Dict:
    """Decode one message into a plain dict via its field table."""
    out: Dict = {}
    for fno, wt, val in _iter_fields(data):
        spec = table.get(fno)
        if spec is None:
            continue  # unknown field: skip (forward compat)
        name, kind = spec[0], spec[1]
        repeated = kind.endswith("*")
        base = kind[:-1] if repeated else kind
        if base == "msg":
            v = decode_message(val, spec[2])
        elif base == "str":
            v = val.decode("utf-8")
        elif base == "float":
            if wt == _WT_LEN:  # packed repeated f32 (proto3 writers)
                vs = [float(x) for x in
                      struct.unpack("<%df" % (len(val) // 4), val)]
                if repeated:
                    out.setdefault(name, []).extend(vs)
                    continue
                v = vs[-1] if vs else 0.0
            elif wt == _WT_32BIT:
                v = struct.unpack("<f", struct.pack("<I", val))[0]
            else:
                v = float(val)
        elif base in ("int", "enum", "bool"):
            if wt == _WT_LEN:  # packed repeated varints
                pos, vs = 0, []
                while pos < len(val):
                    x, pos = _read_varint(val, pos)
                    vs.append(bool(x) if base == "bool"
                              else _to_signed(x))
                if repeated:
                    out.setdefault(name, []).extend(vs)
                    continue
                v = vs[-1] if vs else (False if base == "bool" else 0)
            elif base == "bool":
                v = bool(val)
            else:
                v = _to_signed(val) if base == "int" else val
        else:
            raise ValueError("bad field kind %r" % kind)
        if repeated:
            out.setdefault(name, []).append(v)
        else:
            out[name] = v
    return out


def encode_message(msg: Dict, table: Dict) -> bytes:
    """Encode a plain dict into proto2 wire bytes via its field table.
    proto2 convention: repeated scalars unpacked."""
    out = bytearray()
    for fno in sorted(table):
        spec = table[fno]
        name, kind = spec[0], spec[1]
        if name not in msg or msg[name] is None:
            continue
        repeated = kind.endswith("*")
        base = kind[:-1] if repeated else kind
        vals = msg[name] if repeated else [msg[name]]
        for v in vals:
            if base == "msg":
                payload = encode_message(v, spec[2])
                _write_varint(out, (fno << 3) | _WT_LEN)
                _write_varint(out, len(payload))
                out.extend(payload)
            elif base == "str":
                payload = v.encode("utf-8")
                _write_varint(out, (fno << 3) | _WT_LEN)
                _write_varint(out, len(payload))
                out.extend(payload)
            elif base == "float":
                _write_varint(out, (fno << 3) | _WT_32BIT)
                out.extend(struct.pack("<f", float(v)))
            elif base in ("int", "enum", "bool"):
                _write_varint(out, (fno << 3) | _WT_VARINT)
                _write_varint(out, int(v))
            else:
                raise ValueError("bad field kind %r" % kind)
    return bytes(out)


# ---------------------------------------------------------------------------
# ProgramDesc dict <-> paddle_tpu Program
# ---------------------------------------------------------------------------

_SERIALIZABLE_ATTR = (int, float, bool, str)


def _attr_to_py(attr: Dict):
    t = attr.get("type", ATTR_INT)
    if t == ATTR_INT:
        return attr.get("i", 0)
    if t == ATTR_FLOAT:
        return attr.get("f", 0.0)
    if t == ATTR_STRING:
        return attr.get("s", "")
    if t == ATTR_INTS:
        return list(attr.get("ints", []))
    if t == ATTR_FLOATS:
        return list(attr.get("floats", []))
    if t == ATTR_STRINGS:
        return list(attr.get("strings", []))
    if t == ATTR_BOOLEAN:
        return bool(attr.get("b", False))
    if t == ATTR_BOOLEANS:
        return [bool(b) for b in attr.get("bools", [])]
    if t == ATTR_BLOCK:
        return ("__block__", attr.get("block_idx", 0))
    if t == ATTR_BLOCKS:
        return ("__blocks__", list(attr.get("blocks_idx", [])))
    if t == ATTR_LONG:
        return attr.get("l", 0)
    if t == ATTR_LONGS:
        return list(attr.get("longs", []))
    raise ValueError("unknown AttrType %r" % t)


def _py_to_attr(name: str, v) -> Dict:
    a: Dict = {"name": name}
    if isinstance(v, bool):
        a["type"], a["b"] = ATTR_BOOLEAN, v
    elif isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            a["type"], a["i"] = ATTR_INT, v
        else:
            a["type"], a["l"] = ATTR_LONG, v
    elif isinstance(v, float):
        a["type"], a["f"] = ATTR_FLOAT, v
    elif isinstance(v, str):
        a["type"], a["s"] = ATTR_STRING, v
    elif isinstance(v, (list, tuple)):
        if all(isinstance(x, bool) for x in v):
            a["type"], a["bools"] = ATTR_BOOLEANS, [bool(x) for x in v]
        elif all(isinstance(x, (int, np.integer)) for x in v):
            vv = [int(x) for x in v]
            if any(not -(1 << 31) <= x < (1 << 31) for x in vv):
                a["type"], a["longs"] = ATTR_LONGS, vv
            else:
                a["type"], a["ints"] = ATTR_INTS, vv
        elif all(isinstance(x, str) for x in v):
            a["type"], a["strings"] = ATTR_STRINGS, list(v)
        else:
            a["type"], a["floats"] = ATTR_FLOATS, [float(x) for x in v]
    else:
        return {}
    return a


def program_to_proto_bytes(program, feed_names=(), fetch_names=()) -> bytes:
    """Serialize a Program as a reference-format ProgramDesc, with
    feed/fetch ops prepended/appended like save_inference_model does
    (reference io.py prepend_feed_ops/append_fetch_ops)."""
    from . import dtypes as _dt

    blocks = []
    for b in program.blocks:
        vars_pb = []
        for name, v in b.vars.items():
            dt = _dt.dtype_to_enum(getattr(v, "dtype", None) or "float32")
            shape = [int(d) for d in (v.shape or ())]
            vars_pb.append({
                "name": name,
                "type": {"type": 7,  # LOD_TENSOR
                         "lod_tensor": {"tensor": {"data_type": dt,
                                                   "dims": shape}}},
                "persistable": bool(getattr(v, "persistable", False)),
            })
        ops_pb = []
        if b.idx == 0:
            vars_pb.append({"name": "feed", "type": {"type": 9},
                            "persistable": True})
            vars_pb.append({"name": "fetch", "type": {"type": 10},
                            "persistable": True})
            for i, fn in enumerate(feed_names):
                ops_pb.append({"type": "feed",
                               "inputs": [{"parameter": "X",
                                           "arguments": ["feed"]}],
                               "outputs": [{"parameter": "Out",
                                            "arguments": [fn]}],
                               "attrs": [{"name": "col", "type": ATTR_INT,
                                          "i": i}]})
        for op in b.ops:
            inputs = [{"parameter": k, "arguments": list(v)}
                      for k, v in sorted(op.inputs.items())]
            outputs = [{"parameter": k, "arguments": list(v)}
                       for k, v in sorted(op.outputs.items())]
            attrs = []
            for k, v in sorted(op.attrs.items()):
                if k.startswith("_"):
                    continue
                if hasattr(v, "idx"):  # sub-block ref
                    attrs.append({"name": k, "type": ATTR_BLOCK,
                                  "block_idx": int(v.idx)})
                    continue
                a = _py_to_attr(k, v)
                if a:
                    attrs.append(a)
            ops_pb.append({"type": op.type, "inputs": inputs,
                           "outputs": outputs, "attrs": attrs})
        if b.idx == 0:
            for i, fn in enumerate(fetch_names):
                ops_pb.append({"type": "fetch",
                               "inputs": [{"parameter": "X",
                                           "arguments": [fn]}],
                               "outputs": [{"parameter": "Out",
                                            "arguments": ["fetch"]}],
                               "attrs": [{"name": "col", "type": ATTR_INT,
                                          "i": i}]})
        blocks.append({
            "idx": b.idx,
            "parent_idx": b.parent_block.idx if b.parent_block else -1,
            "vars": vars_pb, "ops": ops_pb,
        })
    return encode_message({"blocks": blocks,
                           "version": {"version": 0}}, PROGRAM_DESC)


def proto_bytes_to_program(data: bytes):
    """Parse a reference ``__model__`` into (Program, feed_names,
    fetch_names). feed/fetch ops are stripped — the paddle_tpu Executor
    feeds/fetches scope vars directly."""
    from .. import framework
    from . import dtypes as _dt

    desc = decode_message(data, PROGRAM_DESC)
    # version gate, mirroring the JSON path's newer-format rejection:
    # the reference stamps PADDLE_VERSION_INTEGER (major*1e6+minor*1e3+
    # patch, e.g. 1007000 for the fluid 1.7 line this format tracks)
    # and accepts everything older; 2.x programs use a different op
    # surface, so reject those instead of misparsing
    ver = desc.get("version", {}).get("version", 0)
    if ver >= 2000000:
        raise RuntimeError(
            "__model__ program version %d is from the 2.x format line; "
            "this build reads fluid-era (<2.0) models" % ver)
    program = framework.Program()
    # materialize blocks first (sub-block attrs reference by idx)
    while len(program.blocks) < len(desc.get("blocks", [])):
        program._create_block()
        program._rollback()
    feed_names: List[str] = []
    fetch_names: List[str] = []
    for bd in desc.get("blocks", []):
        b = program.blocks[bd["idx"]]
        if bd["idx"] > 0:
            b.parent_idx = bd.get("parent_idx", -1)
        for vd in bd.get("vars", []):
            name = vd["name"]
            if name in ("feed", "fetch"):
                continue
            vt = vd.get("type", {})
            lt = vt.get("lod_tensor") or vt.get("selected_rows") or {}
            td = lt.get("tensor", lt if "data_type" in lt else {})
            shape = tuple(td.get("dims", ()))
            try:
                dtype = _dt.convert_dtype(td["data_type"]) \
                    if "data_type" in td else None
            except (KeyError, ValueError):
                dtype = None
            v = b.create_var(name=name)
            v.shape = shape or None
            v.dtype = dtype
            v.persistable = bool(vd.get("persistable", False))
        for od in bd.get("ops", []):
            typ = od["type"]
            if typ == "feed":
                col = 0
                for a in od.get("attrs", []):
                    if a.get("name") == "col":
                        col = a.get("i", 0)
                out = od.get("outputs", [{}])[0].get("arguments", [""])[0]
                while len(feed_names) <= col:
                    feed_names.append("")
                feed_names[col] = out
                continue
            if typ == "fetch":
                col = 0
                for a in od.get("attrs", []):
                    if a.get("name") == "col":
                        col = a.get("i", 0)
                src = od.get("inputs", [{}])[0].get("arguments", [""])[0]
                while len(fetch_names) <= col:
                    fetch_names.append("")
                fetch_names[col] = src
                continue
            attrs = {}
            for a in od.get("attrs", []):
                v = _attr_to_py(a)
                if isinstance(v, tuple) and v and v[0] == "__block__":
                    v = program.blocks[v[1]]
                elif isinstance(v, tuple) and v and v[0] == "__blocks__":
                    v = [program.blocks[i] for i in v[1]]
                attrs[a["name"]] = v
            op = framework.Operator(b, typ, None, None, attrs)
            op.inputs = {d["parameter"]: list(d.get("arguments", []))
                         for d in od.get("inputs", [])}
            op.outputs = {d["parameter"]: list(d.get("arguments", []))
                          for d in od.get("outputs", [])}
            op._id = program._next_op_id()
            b.ops.append(op)
    return program, [n for n in feed_names if n], \
        [n for n in fetch_names if n]


# ---------------------------------------------------------------------------
# LoDTensor stream format (lod_tensor.cc:219 + tensor_util.cc:383)
# ---------------------------------------------------------------------------


def serialize_lod_tensor(arr: np.ndarray, lod=None) -> bytes:
    from . import dtypes as _dt

    out = bytearray()
    out += struct.pack("<I", 0)                      # LoDTensor version
    lod = lod or []
    out += struct.pack("<Q", len(lod))               # lod_level
    for level in lod:
        out += struct.pack("<Q", len(level) * 8)
        out += np.asarray(level, dtype="<u8").tobytes()
    out += struct.pack("<I", 0)                      # Tensor version
    desc = encode_message(
        {"data_type": _dt.dtype_to_enum(str(arr.dtype)),
         "dims": [int(d) for d in arr.shape]}, TENSOR_DESC)
    out += struct.pack("<i", len(desc))
    out += desc
    out += np.ascontiguousarray(arr).tobytes()
    return bytes(out)


def parse_lod_tensor(data: bytes, pos: int = 0):
    """Returns (array, lod, next_pos)."""
    from . import dtypes as _dt

    (ver,) = struct.unpack_from("<I", data, pos)
    pos += 4
    if ver != 0:
        raise ValueError("unsupported LoDTensor version %d" % ver)
    (lod_level,) = struct.unpack_from("<Q", data, pos)
    pos += 8
    lod = []
    for _ in range(lod_level):
        (nbytes,) = struct.unpack_from("<Q", data, pos)
        pos += 8
        level = np.frombuffer(data, dtype="<u8", count=nbytes // 8,
                              offset=pos)
        pos += nbytes
        lod.append([int(x) for x in level])
    (tver,) = struct.unpack_from("<I", data, pos)
    pos += 4
    if tver != 0:
        raise ValueError("unsupported Tensor version %d" % tver)
    (dlen,) = struct.unpack_from("<i", data, pos)
    pos += 4
    desc = decode_message(data[pos:pos + dlen], TENSOR_DESC)
    pos += dlen
    dtype = np.dtype(_dt.to_numpy_dtype(desc["data_type"]))
    dims = desc.get("dims", [])
    numel = int(np.prod(dims)) if dims else 1
    arr = np.frombuffer(data, dtype=dtype, count=numel,
                        offset=pos).reshape(dims)
    pos += numel * dtype.itemsize
    return arr, lod, pos


def save_combine_bytes(named_arrays) -> bytes:
    """Reference save_combine_op framing: streams back to back, in the
    given order (callers pass sorted names, matching inference/io.cc)."""
    return b"".join(serialize_lod_tensor(np.asarray(arr))
                    for _, arr in named_arrays)


def save_combine(named_arrays, path: str) -> None:
    with open(path, "wb") as f:
        f.write(save_combine_bytes(named_arrays))


def load_combine(path: str, names: List[str]):
    data = open(path, "rb").read()
    pos = 0
    out = {}
    for n in names:
        arr, lod, pos = parse_lod_tensor(data, pos)
        out[n] = arr
    if pos != len(data):
        raise ValueError(
            "combined param file has %d trailing bytes (name list "
            "mismatch?)" % (len(data) - pos))
    return out
