from .quantization_pass import (  # noqa: F401
    ConvertToInt8Pass, QuantizationFreezePass, QuantizationTransformPass,
    TransformForMobilePass, apply_startup_inits)
from .post_training_quantization import (  # noqa: F401
    PostTrainingQuantization)
