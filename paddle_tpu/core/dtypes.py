"""Dtype lattice shared by descs, tensors, and kernels.

Mirrors the VarType.Type dtype enum of the reference proto IR
(/root/reference/paddle/fluid/framework/framework.proto:104) but is backed
directly by numpy/jax dtypes — there is no separate serialization enum
since the IR here is Python-native.
"""
from __future__ import annotations

import numpy as np

# Canonical names (paddle spelling) -> numpy dtype
_NAME2NP = {
    "bool": np.dtype(np.bool_),
    "int8": np.dtype(np.int8),
    "uint8": np.dtype(np.uint8),
    "int16": np.dtype(np.int16),
    "int32": np.dtype(np.int32),
    "int64": np.dtype(np.int64),
    "float16": np.dtype(np.float16),
    "float32": np.dtype(np.float32),
    "float64": np.dtype(np.float64),
}


def _bfloat16():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


# VarType.Type enum values from the reference proto
# (/root/reference/paddle/fluid/framework/framework.proto:104) — accepted
# anywhere a dtype is taken, for attr-level compatibility.
_ENUM2NAME = {
    0: "bool",
    1: "int16",
    2: "int32",
    3: "int64",
    4: "float16",
    5: "float32",
    6: "float64",
    20: "uint8",
    21: "int8",
    22: "bfloat16",
}
_NAME2ENUM = {v: k for k, v in _ENUM2NAME.items()}


def dtype_to_enum(dtype) -> int:
    return _NAME2ENUM[convert_dtype(dtype)]


def convert_dtype(dtype) -> str:
    """Normalise any dtype spelling to the canonical string name."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, (int, np.integer)) and not isinstance(dtype, (bool, np.bool_)):
        return _ENUM2NAME[int(dtype)]
    if isinstance(dtype, str):
        name = dtype.lower()
        if name in ("float", "fp32"):
            name = "float32"
        if name in ("double", "fp64"):
            name = "float64"
        if name in ("half", "fp16"):
            name = "float16"
        if name in ("bf16",):
            name = "bfloat16"
        if name == "bfloat16" or name in _NAME2NP:
            return name
        raise ValueError("unknown dtype %r" % (dtype,))
    np_dtype = np.dtype(dtype) if not hasattr(dtype, "dtype") else np.dtype(dtype.dtype)
    name = np_dtype.name
    if name in _NAME2NP or name == "bfloat16":
        return name
    raise ValueError("unsupported dtype %r" % (dtype,))


def to_numpy_dtype(dtype) -> np.dtype:
    name = convert_dtype(dtype)
    if name == "bfloat16":
        return _bfloat16()
    return _NAME2NP[name]


def is_floating(dtype) -> bool:
    return convert_dtype(dtype) in ("float16", "bfloat16", "float32", "float64")


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in ("int8", "uint8", "int16", "int32", "int64")
