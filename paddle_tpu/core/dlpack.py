"""DLPack tensor interop.

Reference counterpart: paddle/fluid/framework/dlpack_tensor.cc — zero-
copy exchange with other frameworks through the DLPack capsule
protocol. Here LoDTensor's device array goes through jax's dlpack
bridge, so ``to_dlpack(t)`` hands a capsule torch/cupy/numpy consumers
accept, and ``from_dlpack(capsule_or_tensor)`` ingests external tensors
without a host copy where the backend allows it.
"""
from __future__ import annotations

__all__ = ["to_dlpack", "from_dlpack"]


def _as_array(t):
    from .tensor import LoDTensor

    if isinstance(t, LoDTensor):
        return t.array
    return t


def to_dlpack(tensor):
    """LoDTensor / jax array -> DLPack capsule (the legacy exchange
    object dlpack_tensor.cc produces; jax arrays implement the modern
    ``__dlpack__`` protocol, so the capsule comes straight from it)."""
    return _as_array(tensor).__dlpack__()


def from_dlpack(ext) -> "LoDTensor":
    """DLPack capsule (or any __dlpack__ provider, e.g. a torch
    tensor) -> LoDTensor."""
    import jax.dlpack

    from .tensor import LoDTensor

    arr = jax.dlpack.from_dlpack(ext)
    out = LoDTensor()
    out._array = arr
    return out
