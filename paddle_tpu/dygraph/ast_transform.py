"""AST-based dygraph_to_static conversion.

Parity: /root/reference/python/paddle/fluid/dygraph/dygraph_to_static/
(program_translator.py:229, ifelse_transformer.py, loop_transformer.py,
logical_transformer.py). The reference rewrites the Python AST of a
``@declarative`` function so that tensor-dependent ``if``/``while``/
``for`` become ``cond``/``while`` *ops* in the built Program instead of
being specialized away at trace time.

TPU-native stance: the rewritten statements dispatch at RUNTIME on the
condition's type —

- a Python value keeps exact Python semantics (the transform is a
  no-op for shape-static code paths), while
- a static-graph ``Variable`` builds real graph control flow: ``if``
  lowers to a both-branches select (XLA select — the cheap-branch
  TPU idiom, see layers.cond) and ``while``/``for range`` lower to the
  ``while`` op whose sub-block the program compiler turns into
  ``lax.while_loop``.

This keeps data-dependent loops inside ONE compiled XLA program —
the property the reference's AST pass exists to provide — without the
reference's source-codegen machinery (it generates .py files under
/tmp; we compile the transformed AST directly).
"""
from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List, Set

import numpy as np

__all__ = [
    "ast_to_static_func",
    "convert_ifelse",
    "convert_while",
    "convert_for_range",
    "convert_logical_and",
    "convert_logical_or",
    "convert_logical_not",
]


class Dy2StaticError(ValueError):
    """A conversion diagnostic: the function DID use tensor control
    flow, but in a way graph lowering cannot express. Never silently
    degraded to the trace path (which would change semantics)."""


class Undefined:
    """Placeholder for a name assigned inside a loop body but unbound
    before the loop (reference: create_undefined_var)."""

    def __init__(self, name):
        self.name = name

    def __repr__(self):
        return "Undefined(%s)" % self.name


def undefined_guard(thunk, name):
    """``x = undefined_guard(lambda: x, 'x')`` — returns x's current
    value, or an Undefined placeholder when x is unbound. The lambda's
    closure cell is unbound exactly when the name is."""
    try:
        return thunk()
    except NameError:
        return Undefined(name)


def _is_variable(x):
    from .. import framework

    return isinstance(x, framework.Variable)


def _to_bool_var(x):
    from ..layers import tensor as ltensor

    if str(getattr(x, "dtype", "bool")) != "bool":
        return ltensor.cast(x, "bool")
    return x


# ---------------------------------------------------------------------------
# Runtime converters (the ``_jst`` surface the transformed code calls)
# ---------------------------------------------------------------------------


def convert_ifelse(pred, true_fn, false_fn):
    """Reference convert_ifelse (convert_operators.py). Returns the
    tuple of values for the statement's modified names."""
    if _is_variable(pred):
        t_out = true_fn()
        f_out = false_fn()
        return _merge_branch_outputs(pred, t_out, f_out)
    # Python / eager (VarBase __bool__ is concrete under the tracer)
    return true_fn() if pred else false_fn()


def _merge_branch_outputs(pred, t_out, f_out):
    from ..layer_helper import LayerHelper

    helper = LayerHelper("jst_ifelse")
    merged = []
    for t, f in zip(t_out, f_out):
        if t is f:
            merged.append(t)
            continue
        if not (_is_variable(t) or _is_variable(f)):
            if isinstance(t, Undefined) or isinstance(f, Undefined):
                raise Dy2StaticError(
                    "dygraph_to_static: name '%s' is only assigned in one "
                    "branch of a tensor-condition `if`; assign it before "
                    "the `if` so both branches have a value"
                    % (t.name if isinstance(t, Undefined) else f.name))
            # equality merge, array-safe: bare `bool(t == f)` on numpy
            # arrays raises ambiguity — use array_equal there; any
            # other type keeps plain `==` (lists, tuples, np scalars)
            if isinstance(t, np.ndarray) or isinstance(f, np.ndarray):
                equal = (type(t) is type(f)) and np.array_equal(t, f)
            else:
                try:
                    equal = bool(t == f)
                except Exception:
                    equal = False
            if equal:
                merged.append(t)
                continue
            scalar = (bool, int, float)
            if not (isinstance(t, scalar) and isinstance(f, scalar)):
                raise Dy2StaticError(
                    "dygraph_to_static: a tensor-condition `if` assigns "
                    "non-tensor values that differ between branches "
                    "(%r vs %r); graph control flow can only carry "
                    "tensors and numeric scalars" % (t, f))
            # differing scalars (e.g. break/continue guard flags):
            # promote both and select
        t, f = _promote_scalar_pair(t, f)
        out = helper.create_variable_for_type_inference(t.dtype)
        helper.append_op("where",
                         inputs={"Condition": [pred], "X": [t], "Y": [f]},
                         outputs={"Out": [out]})
        merged.append(out)
    return tuple(merged)


def _promote_scalar(v, like=None):
    """Promote a Python scalar loop/branch value to a graph constant."""
    from ..layers import tensor as ltensor

    if _is_variable(v):
        return v
    if isinstance(v, bool):
        return ltensor.fill_constant([1], "bool", float(v))
    if isinstance(v, int):
        return ltensor.fill_constant([1], "int64", float(v))
    if isinstance(v, float):
        return ltensor.fill_constant([1], "float32", v)
    raise Dy2StaticError(
        "dygraph_to_static: cannot carry a %s through graph control "
        "flow; only tensors and int/float/bool scalars are supported"
        % type(v).__name__)


def _promote_scalar_pair(t, f):
    """Promote a branch pair to a COMMON dtype (True vs 0 must not
    become bool-vs-int64 `where` operands)."""
    from ..layers import tensor as ltensor

    def fill(v, dt):
        return ltensor.fill_constant([1], dt, float(v))

    if _is_variable(t) and _is_variable(f):
        return t, f
    if _is_variable(t):
        return t, fill(f, str(t.dtype))
    if _is_variable(f):
        return fill(t, str(f.dtype)), f
    if isinstance(t, float) or isinstance(f, float):
        dt = "float32"
    elif isinstance(t, bool) and isinstance(f, bool):
        dt = "bool"
    else:
        dt = "int64"
    return fill(t, dt), fill(f, dt)


def convert_while(cond_fn, body_fn, loop_vars):
    """Reference convert_while_loop (convert_operators.py:27)."""
    pred = cond_fn(*loop_vars)
    if not _is_variable(pred):
        # exact Python semantics
        loop_vars = tuple(loop_vars)
        while pred:
            loop_vars = tuple(body_fn(*loop_vars))
            pred = cond_fn(*loop_vars)
        return loop_vars
    return _build_while(cond_fn, body_fn, loop_vars)


def _rank1(v):
    """Normalize a 0-d Variable to shape [1]: XLA while carries must be
    shape-stable, and scalar-vs-[1] drift between the initial value and
    a body update would silently force the interpreter fallback."""
    if getattr(v, "shape", None) == ():
        from ..layers import nn as lnn

        return lnn.reshape(v, [1])
    return v


def _build_while(cond_fn, body_fn, loop_vars):
    from ..layers import control_flow as cf
    from ..layers import tensor as ltensor

    for v in loop_vars:
        if isinstance(v, Undefined):
            raise Dy2StaticError(
                "dygraph_to_static: name '%s' is assigned inside a "
                "tensor-condition loop but has no value before it; "
                "initialize it before the loop" % v.name)
    carried = [_rank1(_promote_scalar(v)) for v in loop_vars]
    # Loop-carried vars are mutated in place by the body (`assign` into
    # the parent-scope var — the while op's scope-side-effect contract,
    # reference operators/controlflow/while_op.cc). A carried var that
    # is a feed/parameter must not be clobbered: copy into a fresh var.
    fresh = []
    for v in carried:
        nv = ltensor.assign(v)
        nv.shape = v.shape
        nv.dtype = v.dtype
        fresh.append(nv)
    pred_var = _to_bool_var(cond_fn(*fresh))
    w = cf.While(pred_var)
    with w.block():
        new_vars = body_fn(*fresh)
        if len(new_vars) != len(fresh):
            raise ValueError("loop body must return all loop vars")
        for old, new in zip(fresh, new_vars):
            if new is not old:
                ltensor.assign(_rank1(_promote_scalar(new)), old)
        ltensor.assign(_to_bool_var(cond_fn(*fresh)), pred_var)
    return tuple(fresh)


def convert_for_range(range_args, body_fn, loop_vars):
    """``for i in range(...)`` — tensor trip counts lower to a while
    op; Python trip counts keep Python semantics. ``body_fn`` takes
    (iter_var, *loop_vars) and returns the updated loop_vars tuple.
    Returns (final_iter_value, *updated_loop_vars) so the iteration
    variable stays bound after the loop, as in Python."""
    if len(range_args) == 1:
        start, stop, step = 0, range_args[0], 1
    elif len(range_args) == 2:
        start, stop = range_args
        step = 1
    else:
        start, stop, step = range_args
    if not (_is_variable(start) or _is_variable(stop)
            or _is_variable(step)):
        loop_vars = tuple(loop_vars)
        i = Undefined("<loop target>")  # zero-trip: stays undefined
        for i in range(start, stop, step):
            loop_vars = tuple(body_fn(i, *loop_vars))
        return (i,) + loop_vars

    from ..layers import tensor as ltensor

    def _i64(v):
        if _is_variable(v):
            if str(v.dtype) != "int64":
                return ltensor.cast(v, "int64")
            return v
        return ltensor.fill_constant([1], "int64", float(v))

    start_v, stop_v, step_v = _i64(start), _i64(stop), _i64(step)

    def cond_fn(i, *vs):
        # direction-aware bound: (step>0 and i<stop) or (step<0 and
        # i>stop) — a negative step must terminate, not hang the
        # compiled while loop
        from ..layers import control_flow as cf
        from ..layers import tensor as ltensor

        zero = ltensor.fill_constant([1], "int64", 0.0)
        fwd = cf.logical_and(step_v > zero, i < stop_v)
        bwd = cf.logical_and(step_v < zero, i > stop_v)
        return cf.logical_or(fwd, bwd)

    def wrapped_body(i, *vs):
        out = body_fn(i, *vs)
        return (i + step_v,) + tuple(out)

    results = _build_while(cond_fn, wrapped_body,
                           (start_v,) + tuple(loop_vars))
    # results[0] is the first OUT-of-range counter; Python leaves the
    # target at the last in-range value (zero-trip loops get start -
    # step — a documented deviation, Python would leave it unbound)
    final_i = results[0] - step_v
    return (final_i,) + tuple(results[1:])


def convert_logical_and(x, y_fn):
    if _is_variable(x):
        from ..layers import control_flow as cf

        y = y_fn()
        if not _is_variable(y):
            y = _promote_scalar(bool(y))
        return cf.logical_and(_to_bool_var(x), _to_bool_var(y))
    if not x:
        return x
    return y_fn()


def convert_logical_or(x, y_fn):
    if _is_variable(x):
        from ..layers import control_flow as cf

        y = y_fn()
        if not _is_variable(y):
            y = _promote_scalar(bool(y))
        return cf.logical_or(_to_bool_var(x), _to_bool_var(y))
    if x:
        return x
    return y_fn()


def convert_logical_not(x):
    if _is_variable(x):
        from ..layers import control_flow as cf

        return cf.logical_not(_to_bool_var(x))
    return not x


# ---------------------------------------------------------------------------
# AST analysis helpers
# ---------------------------------------------------------------------------


class _ScopedWalker(ast.NodeVisitor):
    """Walk statements without descending into nested function/class
    scopes (their assignments are not this scope's names)."""

    def visit_FunctionDef(self, node):
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_ClassDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass


class _AssignedNames(_ScopedWalker):
    def __init__(self):
        self.names: Set[str] = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name):
            self.names.add(node.target.id)
        self.generic_visit(node)


class _ReadNames(_ScopedWalker):
    def __init__(self):
        self.names: Set[str] = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.names.add(node.id)

    # reads inside nested lambdas/functions ARE closure reads of this
    # scope; be conservative and include them
    def visit_Lambda(self, node):
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                self.names.add(n.id)


def _assigned(stmts) -> Set[str]:
    w = _AssignedNames()
    for s in stmts:
        w.visit(s)
    return w.names


def _read(nodes) -> Set[str]:
    w = _ReadNames()
    for s in nodes:
        w.visit(s)
    return w.names


class _OwnLoopFlow(_ScopedWalker):
    """Scan a loop body's OWN scope: break/continue not inside nested
    loops; return at any statement depth (it escapes the loop either
    way); 'clean' is False when a break/continue hides under a
    non-If compound (try/with) the guard rewriter can't wrap."""

    def __init__(self):
        self.has_break = False
        self.has_continue = False
        self.has_return = False
        self.clean = True
        self._if_depth_only = True

    def visit_Return(self, node):
        self.has_return = True

    def visit_Break(self, node):
        self.has_break = True
        if not self._if_depth_only:
            self.clean = False

    def visit_Continue(self, node):
        self.has_continue = True
        if not self._if_depth_only:
            self.clean = False

    def visit_If(self, node):
        for s in node.body + node.orelse:
            self.visit(s)

    def _compound(self, node):
        prev = self._if_depth_only
        self._if_depth_only = False
        self.generic_visit(node)
        self._if_depth_only = prev

    visit_With = _compound
    visit_Try = _compound

    def visit_While(self, node):
        # nested loop BODY: its own break/continue scope — but a
        # return inside it still escapes THIS loop (skip nested
        # functions). The nested loop's else: clause is DIFFERENT:
        # Python binds break/continue there to the OUTER loop — the
        # guard rewriter can't wrap those, so they mark us not-clean.
        stack = list(node.body)
        while stack:
            s = stack.pop()
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(s, ast.Return):
                self.has_return = True
            stack.extend(ast.iter_child_nodes(s))
        prev = self._if_depth_only
        self._if_depth_only = False  # break in else: -> clean = False
        for s in node.orelse:
            self.visit(s)
        self._if_depth_only = prev

    visit_For = visit_While


def _scan_own_loop_flow(stmts) -> "_OwnLoopFlow":
    w = _OwnLoopFlow()
    for s in stmts:
        w.visit(s)
    return w


def _flag_assign(name, value: bool):
    return ast.Assign(targets=[_name(name, ast.Store())],
                      value=ast.Constant(value=value))


def _rewrite_break_continue(stmts, brk, cont, guard_flags):
    """Replace break/continue with guard-flag sets and wrap statement
    suffixes in `if not (flags):` (reference
    break_continue_transformer.py). Returns (new_stmts, may_set)."""
    out = []
    for idx, s in enumerate(stmts):
        if isinstance(s, ast.Break):
            out.append(_flag_assign(brk, True))
            return out, True  # anything after a bare break is dead
        if isinstance(s, ast.Continue):
            out.append(_flag_assign(cont, True))
            return out, True
        if isinstance(s, ast.If):
            body, hit_b = _rewrite_break_continue(
                s.body, brk, cont, guard_flags)
            orelse, hit_o = _rewrite_break_continue(
                s.orelse, brk, cont, guard_flags)
            s = ast.If(test=s.test, body=body,
                       orelse=orelse or [])
            out.append(s)
            if hit_b or hit_o:
                rest, _ = _rewrite_break_continue(
                    stmts[idx + 1:], brk, cont, guard_flags)
                if rest:
                    # guard: not flag1 and not flag2 ...
                    test = None
                    for fl in guard_flags:
                        term = ast.UnaryOp(op=ast.Not(),
                                           operand=_name(fl))
                        test = term if test is None else ast.BoolOp(
                            op=ast.And(), values=[test, term])
                    out.append(ast.If(test=test, body=rest, orelse=[]))
                return out, True
            continue
        out.append(s)
    return out, False


def _has_flow_escape(stmts) -> bool:
    """return/break/continue directly in this statement list (not in
    nested loops for break/continue, not in nested functions)."""

    class W(_ScopedWalker):
        found = False

        def visit_Return(self, node):
            self.found = True

        def visit_Break(self, node):
            self.found = True

        def visit_Continue(self, node):
            self.found = True

        def visit_While(self, node):  # its own break/continue scope
            for t in node.body + node.orelse:
                if any(isinstance(n, ast.Return) for n in ast.walk(t)):
                    self.found = True

        visit_For = visit_While

    w = W()
    for s in stmts:
        w.visit(s)
    return w.found


# ---------------------------------------------------------------------------
# The transformer
# ---------------------------------------------------------------------------


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_attr(fn_name):
    return ast.Attribute(value=_name("_jst"), attr=fn_name, ctx=ast.Load())


def _ret_tuple(names: List[str]):
    return ast.Return(value=ast.Tuple(
        elts=[_name(n) for n in names], ctx=ast.Load()))


def _guard_call(n: str):
    """``_jst.undefined_guard(lambda: n, 'n')`` — n's current outer
    value, or Undefined when unbound."""
    return ast.Call(
        func=_jst_attr("undefined_guard"),
        args=[ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[],
                               kwonlyargs=[], kw_defaults=[],
                               defaults=[]),
            body=_name(n)),
            ast.Constant(value=n)],
        keywords=[])


def _def_with_guard_defaults(name: str, argnames: List[str], body):
    """Branch function whose params DEFAULT to the enclosing scope's
    current values (evaluated at def time). This is how a branch body
    that assigns `s` can still read the pre-branch `s`: as a parameter,
    not a closure read (which Python forbids once the name is local)."""
    return ast.FunctionDef(
        name=name,
        args=ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=a) for a in argnames],
            kwonlyargs=[], kw_defaults=[],
            defaults=[_guard_call(a) for a in argnames]),
        body=body or [ast.Pass()],
        decorator_list=[])


def _tuple_store(names: List[str]):
    return ast.Tuple(elts=[_name(n, ast.Store()) for n in names],
                     ctx=ast.Store())


def _def(name: str, argnames: List[str], body):
    return ast.FunctionDef(
        name=name,
        args=ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=a) for a in argnames],
            kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=body or [ast.Pass()],
        decorator_list=[])


class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites if/while/for with potentially-tensor conditions into
    _jst.convert_* calls (reference ifelse/loop/logical transformers)."""

    def __init__(self):
        self._n = 0

    def _uid(self):
        self._n += 1
        return self._n

    # -- logical operators ------------------------------------------------

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        conv = ("convert_logical_and" if isinstance(node.op, ast.And)
                else "convert_logical_or")
        out = node.values[0]
        for v in node.values[1:]:
            out = ast.Call(
                func=_jst_attr(conv),
                args=[out, ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]),
                    body=v)],
                keywords=[])
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(func=_jst_attr("convert_logical_not"),
                            args=[node.operand], keywords=[])
        return node

    # -- if/else ----------------------------------------------------------

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_flow_escape(node.body) or _has_flow_escape(node.orelse):
            # early return/break in a branch: keep the Python `if`
            # (valid for Python conditions; a tensor condition here
            # raises via Variable.__bool__ with a pointer to this
            # limitation — same contract as jax.jit)
            return node
        uid = self._uid()
        # exclude synthetic _jst_* temporaries (from nested transformed
        # ifs) — they are dead after their converted statement and must
        # not cross the branch merge (mirrors visit_While's filter)
        modified = sorted(n for n in
                          (_assigned(node.body) | _assigned(node.orelse))
                          if not n.startswith("_jst_"))
        pred_name = "_jst_pred_%d" % uid
        true_name = "_jst_true_%d" % uid
        false_name = "_jst_false_%d" % uid
        stmts = [
            ast.Assign(targets=[_name(pred_name, ast.Store())],
                       value=node.test),
            _def_with_guard_defaults(
                true_name, modified,
                list(node.body) + [_ret_tuple(modified)]),
            _def_with_guard_defaults(
                false_name, modified,
                list(node.orelse) + [_ret_tuple(modified)]),
        ]
        call = ast.Call(func=_jst_attr("convert_ifelse"),
                        args=[_name(pred_name), _name(true_name),
                              _name(false_name)],
                        keywords=[])
        if modified:
            stmts.append(ast.Assign(targets=[_tuple_store(modified)],
                                    value=call))
        else:
            stmts.append(ast.Expr(value=call))
        return stmts

    # -- while ------------------------------------------------------------

    def visit_While(self, node):
        if node.orelse:
            self.generic_visit(node)
            return node
        flow = _scan_own_loop_flow(node.body)
        pre = []
        if flow.has_break or flow.has_continue:
            if flow.has_return or not flow.clean:
                # return-in-loop (or break under try/with) stays a
                # Python loop — tensor conditions get the
                # Variable.__bool__ guidance error
                self.generic_visit(node)
                return node
            fuid = self._uid()
            brk = "_loopflag_brk_%d" % fuid      # NOT _jst_: must carry
            cont = "_loopflag_cont_%d" % fuid
            flags = ([brk] if flow.has_break else []) + \
                ([cont] if flow.has_continue else [])
            body, _ = _rewrite_break_continue(node.body, brk, cont,
                                              flags)
            if flow.has_continue:
                # continue only skips the REST of the iteration
                body = [_flag_assign(cont, False)] + body
            if flow.has_break:
                node.test = ast.BoolOp(
                    op=ast.And(),
                    values=[ast.UnaryOp(op=ast.Not(),
                                        operand=_name(brk)),
                            node.test])
                pre.append(_flag_assign(brk, False))
            if flow.has_continue:
                pre.append(_flag_assign(cont, False))
            node = ast.While(test=node.test, body=body, orelse=[])
        self.generic_visit(node)
        if _has_flow_escape(node.body):
            return pre + [node] if pre else node
        uid = self._uid()
        # synthetic _jst_* temporaries (from nested transformed ifs)
        # are recomputed every iteration — never loop-carried
        loop_vars = sorted(n for n in _assigned(node.body)
                           if not n.startswith("_jst_"))
        if not loop_vars:
            return node
        cond_name = "_jst_cond_%d" % uid
        body_name = "_jst_body_%d" % uid
        stmts = []
        for lv in loop_vars:
            # x = undefined_guard(lambda: x, 'x') — Undefined when unbound
            stmts.append(ast.Assign(
                targets=[_name(lv, ast.Store())],
                value=ast.Call(
                    func=_jst_attr("undefined_guard"),
                    args=[ast.Lambda(
                        args=ast.arguments(posonlyargs=[], args=[],
                                           kwonlyargs=[], kw_defaults=[],
                                           defaults=[]),
                        body=_name(lv)),
                        ast.Constant(value=lv)],
                    keywords=[])))
        stmts.append(_def(cond_name, loop_vars,
                          [ast.Return(value=node.test)]))
        stmts.append(_def(body_name, loop_vars,
                          list(node.body) + [_ret_tuple(loop_vars)]))
        stmts.append(ast.Assign(
            targets=[_tuple_store(loop_vars)],
            value=ast.Call(
                func=_jst_attr("convert_while"),
                args=[_name(cond_name), _name(body_name),
                      ast.Tuple(elts=[_name(v) for v in loop_vars],
                                ctx=ast.Load())],
                keywords=[])))
        return pre + stmts

    # -- for range --------------------------------------------------------

    def visit_For(self, node):
        self.generic_visit(node)
        if (node.orelse or _has_flow_escape(node.body)
                or not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or node.iter.keywords):
            return node
        uid = self._uid()
        target = node.target.id
        loop_vars = sorted(n for n in _assigned(node.body) - {target}
                           if not n.startswith("_jst_"))
        body_name = "_jst_forbody_%d" % uid
        stmts = []
        for lv in loop_vars:
            stmts.append(ast.Assign(
                targets=[_name(lv, ast.Store())],
                value=ast.Call(
                    func=_jst_attr("undefined_guard"),
                    args=[ast.Lambda(
                        args=ast.arguments(posonlyargs=[], args=[],
                                           kwonlyargs=[], kw_defaults=[],
                                           defaults=[]),
                        body=_name(lv)),
                        ast.Constant(value=lv)],
                    keywords=[])))
        stmts.append(_def(body_name, [target] + loop_vars,
                          list(node.body) + [_ret_tuple(loop_vars)]))
        stmts.append(ast.Assign(
            targets=[_tuple_store([target] + loop_vars)],
            value=ast.Call(
                func=_jst_attr("convert_for_range"),
                args=[ast.Tuple(elts=list(node.iter.args),
                                ctx=ast.Load()),
                      _name(body_name),
                      ast.Tuple(elts=[_name(v) for v in loop_vars],
                                ctx=ast.Load())],
                keywords=[])))
        return stmts


# ---------------------------------------------------------------------------
# Function compilation
# ---------------------------------------------------------------------------


class _JstModule:
    """The ``_jst`` namespace injected into transformed functions."""

    convert_ifelse = staticmethod(convert_ifelse)
    convert_while = staticmethod(convert_while)
    convert_for_range = staticmethod(convert_for_range)
    convert_logical_and = staticmethod(convert_logical_and)
    convert_logical_or = staticmethod(convert_logical_or)
    convert_logical_not = staticmethod(convert_logical_not)
    undefined_guard = staticmethod(undefined_guard)


_JST = _JstModule()


def ast_to_static_func(fn):
    """Return (converted_fn, True) or (fn, False) when the source is
    unavailable (builtins, exec-defined, C extensions)."""
    try:
        src = inspect.getsource(fn)
    except (OSError, TypeError):
        return fn, False
    src = textwrap.dedent(src)
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return fn, False
    func_def = tree.body[0]
    if not isinstance(func_def, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn, False
    func_def.decorator_list = []
    _ControlFlowTransformer().visit(func_def)

    freevars = list(fn.__code__.co_freevars)
    if freevars:
        # rebuild the closure: wrap in a factory taking the free names
        factory = _def("_jst_factory", freevars,
                       [func_def, ast.Return(value=_name(func_def.name))])
        mod = ast.Module(body=[factory], type_ignores=[])
    else:
        mod = ast.Module(body=[func_def], type_ignores=[])
    ast.fix_missing_locations(mod)

    class _Globals(dict):
        """Live view over the module globals: names defined AFTER the
        decorator runs (later helpers, late imports) must resolve —
        a dict snapshot would freeze the module at decoration time.
        LOAD_GLOBAL honors __missing__ on dict subclasses."""

        def __init__(self, base):
            super().__init__()
            self._base = base

        def __missing__(self, key):
            return self._base[key]

    glb = _Globals(getattr(fn, "__globals__", {}))
    glb["_jst"] = _JST
    code = compile(mod, filename="<dygraph_to_static:%s>" % fn.__name__,
                   mode="exec")
    # exec into ONE namespace so recursive self-references resolve
    exec(code, glb)
    if freevars:
        try:
            # NOTE: a decoration-time snapshot — a free variable
            # rebound later is not seen by the static path (the trace
            # fallback would see it); empty cells (self-recursion,
            # late binding) mean the AST path cannot be built
            cells = [c.cell_contents for c in fn.__closure__]
        except ValueError:  # empty cell
            return fn, False
        new_fn = glb["_jst_factory"](*cells)
    else:
        new_fn = glb[func_def.name]
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    return new_fn, True
