"""Dygraph layer-class tail: Conv3D, Conv3DTranspose,
BilinearTensorProduct, NCE, SequenceConv, RowConv, SpectralNorm,
TreeConv (reference python/paddle/fluid/dygraph/nn.py class set)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.dygraph import nn as dnn


def _v(arr):
    return fluid.dygraph.to_variable(np.asarray(arr))


class TestDygraphNnTail:
    def test_conv3d_forward(self):
        with fluid.dygraph.guard():
            m = dnn.Conv3D(2, 4, filter_size=3, padding=1)
            out = m(_v(np.random.rand(1, 2, 4, 4, 4).astype("float32")))
            assert out.shape == (1, 4, 4, 4, 4)

    def test_conv3d_transpose_forward(self):
        with fluid.dygraph.guard():
            m = dnn.Conv3DTranspose(2, 3, filter_size=2, stride=2)
            out = m(_v(np.random.rand(1, 2, 3, 3, 3).astype("float32")))
            assert out.shape[1] == 3 and out.shape[2] == 6

    def test_bilinear_tensor_product(self):
        with fluid.dygraph.guard():
            m = dnn.BilinearTensorProduct(3, 4, 5)
            out = m(_v(np.random.rand(2, 3).astype("float32")),
                    _v(np.random.rand(2, 4).astype("float32")))
            assert out.shape == (2, 5)

    def test_nce_loss_positive(self):
        with fluid.dygraph.guard():
            m = dnn.NCE(num_total_classes=20, dim=6, num_neg_samples=5)
            cost = m(_v(np.random.rand(4, 6).astype("float32")),
                     _v(np.array([[1], [2], [3], [4]], "int64")))
            arr = np.asarray(cost.numpy())
            assert arr.shape[0] == 4 and np.all(arr > 0)

    def test_sequence_conv(self):
        with fluid.dygraph.guard():
            m = dnn.SequenceConv(num_filters=5, filter_size=3,
                                 input_dim=4)
            out = m(_v(np.random.rand(6, 4).astype("float32")))
            assert out.shape == (6, 5)

    def test_row_conv(self):
        with fluid.dygraph.guard():
            m = dnn.RowConv(future_context_size=2, input_dim=4)
            # dense layout [batch, time, dim] (row_conv_op.cc)
            out = m(_v(np.random.rand(1, 6, 4).astype("float32")))
            assert out.shape == (1, 6, 4)

    def test_spectral_norm_unit_sigma(self):
        with fluid.dygraph.guard():
            m = dnn.SpectralNorm([4, 6], dim=0, power_iters=8)
            w = np.random.RandomState(0).rand(4, 6).astype("float32")
            out = np.asarray(m(_v(w)).numpy())
            # normalized weight has largest singular value ~1
            s = np.linalg.svd(out, compute_uv=False)[0]
            assert abs(s - 1.0) < 0.2, s

    def test_tree_conv(self):
        with fluid.dygraph.guard():
            m = dnn.TreeConv(feature_size=4, output_size=3,
                             num_filters=2, max_depth=2)
            nodes = np.random.rand(1, 5, 4).astype("float32")
            edges = np.array([[[1, 2], [1, 3], [3, 4], [3, 5]]],
                             "int32")
            out = m(_v(nodes), _v(edges))
            assert np.asarray(out.numpy()).shape[:2] == (1, 5)
