"""dygraph.Layer — the eager module base class.

Parity: /root/reference/python/paddle/fluid/dygraph/layers.py (Layer:
sublayers/parameters traversal, add_parameter/add_sublayer, state_dict,
train/eval, forward hooks).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from .. import framework
from ..utils import unique_name
from .varbase import ParamBase, VarBase

__all__ = ["Layer"]


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._full_name = unique_name.generate(
            name_scope or self.__class__.__name__.lower())
        self._dtype = dtype
        self._parameters: "OrderedDict[str, ParamBase]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._buffers: "OrderedDict[str, VarBase]" = OrderedDict()
        self.training = True
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()

    def full_name(self):
        return self._full_name

    # -- mode -------------------------------------------------------------
    def train(self):
        self.training = True
        tracer = framework._dygraph_tracer()
        if tracer:
            tracer.train_mode = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        tracer = framework._dygraph_tracer()
        if tracer:
            tracer.train_mode = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- registration -----------------------------------------------------
    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, ParamBase):
            raise TypeError("parameter must be ParamBase")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, VarBase):
            tensor = VarBase(np.asarray(tensor), stop_gradient=True)
        self._buffers[name] = tensor
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from ..layer_helper import LayerHelper

        helper = LayerHelper(self.full_name(), param_attr=attr)
        from ..param_attr import ParamAttr

        return helper.create_parameter(ParamAttr._to_attr(attr), list(shape),
                                       dtype or self._dtype, is_bias,
                                       default_initializer)

    # -- traversal --------------------------------------------------------
    def parameters(self, include_sublayers=True) -> List[ParamBase]:
        out = [p for p in self._parameters.values() if p is not None]
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.parameters())
        return out

    def named_parameters(self, prefix="", include_sublayers=True):
        for name, p in self._parameters.items():
            if p is not None:
                yield (prefix + name if not prefix else prefix + "." + name), p
        if include_sublayers:
            for lname, l in self._sub_layers.items():
                sub_prefix = lname if not prefix else prefix + "." + lname
                yield from l.named_parameters(sub_prefix)

    def sublayers(self, include_sublayers=True) -> List["Layer"]:
        out = []
        for l in self._sub_layers.values():
            out.append(l)
            if include_sublayers:
                out.extend(l.sublayers())
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            sub = name if not prefix else prefix + "." + name
            yield from l.named_sublayers(sub, include_self=True)

    # -- state dict -------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix=""):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self._parameters.items():
            if p is not None:
                dest[structured_name_prefix + name] = p
        for name, b in self._buffers.items():
            if b is not None:
                dest[structured_name_prefix + name] = b
        if include_sublayers:
            for lname, l in self._sub_layers.items():
                l.state_dict(dest, True, structured_name_prefix + lname + ".")
        return dest

    def set_dict(self, state_dict, include_sublayers=True,
                 use_structured_name=True):
        own = self.state_dict()
        for key, value in state_dict.items():
            if key in own:
                arr = value.numpy() if isinstance(value, VarBase) else np.asarray(value)
                own[key].set_value(arr)

    set_state_dict = set_dict
    load_dict = set_dict

    # -- call -------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            hook_result = hook(self, inputs)
            if hook_result is not None:
                inputs = hook_result
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            hook_result = hook(self, inputs, outputs)
            if hook_result is not None:
                outputs = hook_result
        return outputs

    # -- attribute magic --------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, ParamBase):
            object.__getattribute__(self, "_parameters")[name] = value
        elif isinstance(value, Layer):
            object.__getattribute__(self, "_sub_layers")[name] = value
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d and name in d:
                return d[name]
        raise AttributeError("%s has no attribute %r"
                             % (type(self).__name__, name))

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()
