"""Save a tiny UCI-housing regression inference model for the R demo
(counterpart of the reference's r/example/mobilenet.py model prep)."""
import os

import numpy as np

import paddle_tpu as fluid


def main(out_dir="data/uci_housing_model"):
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        x = fluid.layers.data("x", shape=[13], dtype="float32")
        y = fluid.layers.fc(x, size=1)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    os.makedirs(out_dir, exist_ok=True)
    fluid.io.save_inference_model(out_dir, ["x"], [y], exe,
                                  main_program=main_prog)
    np.savetxt(os.path.join(out_dir, "data.txt"),
               np.random.RandomState(0).rand(13).astype("float32"))
    print("model + sample input saved under", out_dir)


if __name__ == "__main__":
    main()
