"""IrGraph/pass infrastructure + slim quantization.

Covers: program->graph->program round-trip fidelity, the fc fuse pass
rewrite, QAT transform (fake quant/dequant insertion + STE training),
freeze to an int-level inference graph, and post-training quantization
accuracy on a small net.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib.slim.quantization import (
    PostTrainingQuantization, QuantizationFreezePass,
    QuantizationTransformPass, apply_startup_inits)
from paddle_tpu.ir import IrGraph, PassRegistry, apply_pass

B, D, H = 4, 6, 8


def _small_net():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[B, D], dtype="float32")
        h = fluid.layers.fc(x, size=H, act="relu")
        out = fluid.layers.fc(h, size=2)
    return prog, startup, out


def test_irgraph_round_trip_runs_identically():
    prog, startup, out = _small_net()
    rebuilt = IrGraph(prog).to_program()
    xb = np.random.RandomState(0).randn(B, D).astype("float32")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (a,) = exe.run(prog, feed={"x": xb}, fetch_list=[out])
        (b,) = exe.run(rebuilt, feed={"x": xb}, fetch_list=[out.name])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_fc_fuse_pass():
    prog, startup, out = _small_net()
    fused = apply_pass(prog, "fc_fuse_pass")
    types = [op.type for op in fused.global_block().ops]
    assert "fc" in types
    assert "mul" not in types and "elementwise_add" not in types
    xb = np.random.RandomState(1).randn(B, D).astype("float32")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (a,) = exe.run(prog, feed={"x": xb}, fetch_list=[out])
        (b,) = exe.run(fused, feed={"x": xb}, fetch_list=[out.name])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                               atol=1e-6)


def test_graph_viz_pass(tmp_path):
    prog, _, _ = _small_net()
    p = PassRegistry._passes["graph_viz_pass"](str(tmp_path), "net")
    p.apply(IrGraph(prog))
    dot = (tmp_path / "net.dot").read_text()
    assert "digraph" in dot and "mul" in dot


def test_qat_transform_inserts_fake_ops_and_trains():
    NB = 32
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[NB, D], dtype="float32")
        y = fluid.data(name="y", shape=[NB, 1], dtype="float32")
        pred = fluid.layers.fc(fluid.layers.fc(x, size=H, act="relu"),
                               size=1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))

    graph = IrGraph(prog)
    transform = QuantizationTransformPass(
        activation_quantize_type="moving_average_abs_max")
    qprog = transform.apply(graph).to_program()
    types = [op.type for op in qprog.global_block().ops]
    assert "fake_quantize_moving_average_abs_max" in types
    assert "fake_quantize_abs_max" in types  # weights
    assert "fake_dequantize_max_abs" in types

    # train the transformed program: STE must pass gradients through
    with fluid.program_guard(qprog, startup):
        qloss = qprog.global_block().var(loss.name)
        fluid.optimizer.SGD(0.02).minimize(qloss)
    scope = fluid.Scope()
    rng = np.random.RandomState(2)
    W = rng.randn(D, 1).astype("float32")
    # fixed batch: isolates STE gradient flow from minibatch noise
    xb = rng.randn(NB, D).astype("float32")
    yb = xb @ W
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        apply_startup_inits(graph, scope)
        losses = []
        for _ in range(60):
            (l,) = exe.run(qprog, feed={"x": xb, "y": yb},
                           fetch_list=[qloss])
            losses.append(float(np.asarray(l).ravel()[0]))
    assert losses[-1] < losses[0] * 0.2, losses[::15]


def test_freeze_pass_produces_int_weights_and_close_outputs():
    prog, startup, out = _small_net()
    xb = np.random.RandomState(3).randn(B, D).astype("float32")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (ref,) = exe.run(prog, feed={"x": xb}, fetch_list=[out])

        graph = IrGraph(prog, for_test=True)
        transform = QuantizationTransformPass(scope=scope)
        graph = transform.apply(graph)
        apply_startup_inits(graph, scope)
        freeze = QuantizationFreezePass(scope=scope, place=None)
        graph = freeze.apply(graph)
        frozen = graph.to_program()
        types = [op.type for op in frozen.global_block().ops]
        assert not any(t.startswith("fake_quantize") for t in types)
        assert "fake_dequantize_max_abs" in types
        # weights in scope are now integer levels
        wname = prog.all_parameters()[0].name
        w = np.asarray(scope.find_var(wname).raw().array)
        assert np.abs(w - np.round(w)).max() < 1e-6
        assert np.abs(w).max() <= 127
        (got,) = exe.run(frozen, feed={"x": xb}, fetch_list=[out.name])
    ref, got = np.asarray(ref), np.asarray(got)
    denom = max(np.abs(ref).max(), 1e-3)
    assert np.abs(ref - got).max() / denom < 0.1, (ref, got)


@pytest.mark.parametrize("algo", ["abs_max", "KL"])
def test_post_training_quantization(algo):
    prog, startup, out = _small_net()
    rng = np.random.RandomState(4)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        # pin the executor RNG stream: initializer draws come from it,
        # and KL calibration's 10% tolerance is order-sensitive without
        # a fixed parameter draw (suite-order flake otherwise)
        exe._core.rng.seed = 20260730
        exe._core.rng.step = 0
        exe.run(startup)
        xb = rng.randn(B, D).astype("float32")
        (ref,) = exe.run(prog, feed={"x": xb}, fetch_list=[out])

        ptq = PostTrainingQuantization(
            exe, prog, scope, ["x"], out.name,
            lambda: ([rng.randn(B, D).astype("float32")]
                     for _ in range(4)),
            batch_nums=4, algo=algo)
        qprog = ptq.quantize()
        types = [op.type for op in qprog.global_block().ops]
        assert "fake_quantize_range_abs_max" in types  # static act scales
        (got,) = exe.run(qprog, feed={"x": xb}, fetch_list=[out.name])
        # calibrated scales must be LIVE: clobbering one changes output
        import jax.numpy as jnp

        sv = scope.find_var("x.scale")
        assert sv is not None
        orig = np.asarray(sv.get_tensor().numpy()).copy()
        sv.get_tensor().set(jnp.asarray(orig * 1e-3))
        (poisoned,) = exe.run(qprog, feed={"x": xb},
                              fetch_list=[out.name])
        assert not np.allclose(np.asarray(poisoned), np.asarray(got))
        sv.get_tensor().set(jnp.asarray(orig))
    ref, got = np.asarray(ref), np.asarray(got)
    denom = max(np.abs(ref).max(), 1e-3)
    assert np.abs(ref - got).max() / denom < 0.15, (ref, got)
