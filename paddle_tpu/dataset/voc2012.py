"""VOC2012 segmentation reader creators (reference
python/paddle/dataset/voc2012.py).

Sample contract: (image float32[3,H,W], label uint8[H,W] class mask).
Synthetic fallback: images with one colored rectangle whose class id
matches the mask region, deterministic.
"""
from __future__ import annotations

import os
import tarfile

import numpy as np

from .common import DATA_HOME
from .image import load_image_bytes, to_chw

__all__ = ["train", "test", "val"]

_CLASSES = 21


def _archive():
    p = os.path.join(DATA_HOME, "voc2012", "VOCtrainval_11-May-2012.tar")
    return p if os.path.exists(p) else None


def _synthetic_reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            cls = int(rng.randint(1, _CLASSES))
            img = (rng.rand(48, 48, 3) * 40).astype("uint8")
            mask = np.zeros((48, 48), "uint8")
            y, x = int(rng.randint(4, 24)), int(rng.randint(4, 24))
            img[y:y + 16, x:x + 16, cls % 3] += np.uint8(150)
            mask[y:y + 16, x:x + 16] = cls
            yield to_chw(img).astype("float32") / 255.0, mask

    return reader


def _tar_reader(split):
    def reader():
        with tarfile.open(_archive(), mode="r") as f:
            seg = "VOCdevkit/VOC2012/ImageSets/Segmentation/%s.txt" % split
            names = f.extractfile(seg).read().decode().split()
            for name in names:
                jpg = f.extractfile(
                    "VOCdevkit/VOC2012/JPEGImages/%s.jpg" % name).read()
                png = f.extractfile(
                    "VOCdevkit/VOC2012/SegmentationClass/%s.png"
                    % name).read()
                img = load_image_bytes(jpg)
                # P-mode palette PNG: the raw indices ARE the class ids
                # (convert("L") would turn them into luminance garbage)
                import io as _io

                from PIL import Image

                mask = np.asarray(Image.open(_io.BytesIO(png)))
                yield to_chw(img).astype("float32") / 255.0, \
                    mask.astype("uint8")

    return reader


def train():
    return _tar_reader("train") if _archive() else \
        _synthetic_reader(512, seed=90)


def val():
    return _tar_reader("val") if _archive() else \
        _synthetic_reader(64, seed=91)


def test():
    return _tar_reader("val") if _archive() else \
        _synthetic_reader(64, seed=92)
