"""Static-graph autodiff: append_backward / gradients.

Behavioral parity with /root/reference/python/paddle/fluid/backward.py
(:1145 append_backward, :366 _addup_repetitive_outputs_, :448
_remove_no_grad_branch_): walks the block in reverse, appends
``<type>_grad`` ops, inserts ``sum`` ops where a forward var fans out to
several consumers, and respects stop_gradient / no_grad_set.

The grad ops themselves are the auto-VJP ops from the registry (or
hand-registered customs), so unlike the reference there is no per-op C++
GradOpMaker protocol to mirror — the maker here only decides *wiring*
(which slots are bound), and shapes are copied from the forward vars
instead of re-inferred.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

from . import framework
from .core.registry import GRAD_SUFFIX, OpInfoMap, ensure_grad_op
from .utils import unique_name


def _find_op_path(block, loss_name: str, req: Set[str]) -> List[int]:
    """Indices of ops that both (a) depend on a grad-requiring var and
    (b) contribute to the loss."""
    # forward reachability of req
    contributes: Set[str] = set(req)
    fwd_ops: Set[int] = set()
    for i, op in enumerate(block.ops):
        if any(n in contributes for n in op.input_arg_names):
            fwd_ops.add(i)
            contributes.update(op.output_arg_names)
    # backward reachability from loss
    needed: Set[str] = {loss_name}
    path: List[int] = []
    for i in reversed(range(len(block.ops))):
        op = block.ops[i]
        if i in fwd_ops and any(n in needed for n in op.output_arg_names):
            path.append(i)
            needed.update(op.input_arg_names)
    return list(reversed(path))


def _requires_grad_set(block, parameter_list=None, no_grad_set=None) -> Set[str]:
    no_grad = set(no_grad_set or ())
    req: Set[str] = set()
    if parameter_list is not None:
        for p in parameter_list:
            name = p if isinstance(p, str) else p.name
            if name not in no_grad:
                req.add(name)
    else:
        for p in block.program.all_parameters():
            if getattr(p, "trainable", True) and not p.stop_gradient \
                    and p.name not in no_grad:
                req.add(p.name)
    # any non-stop-gradient var is a valid diff leaf too (matches
    # reference: stop_gradient=False inputs get gradients)
    for v in block.vars.values():
        if not v.stop_gradient and v.name not in no_grad:
            req.add(v.name)
    return req


def _ensure_grad_var(block, fwd_name: str, grad_name: str):
    fwd = block._find_var_recursive(fwd_name)
    if block.has_var_local(grad_name):
        return block.vars[grad_name]
    v = block.create_var(
        name=grad_name,
        shape=fwd.shape if fwd is not None else None,
        dtype=fwd.dtype if fwd is not None else "float32",
        persistable=False,
        # grad vars are differentiable quantities: a later
        # append_backward over this program (gradient penalty /
        # grad-of-grad) must be able to flow gradients through them —
        # stop_gradient=True here would put every @GRAD var in that
        # pass's no_grad set and silently sever the double-grad path
        stop_gradient=False,
    )
    return v


def append_backward(
    loss,
    parameter_list=None,
    no_grad_set=None,
    callbacks=None,
    checkpoints=None,
):
    """Append grad ops computing d(loss)/d(var); returns
    [(param, param_grad_var)] like the reference (backward.py:1145)."""
    block = loss.block
    program = block.program
    program._appending_grad_times += 1
    # pass-aware grad naming (reference backward.py _rename_grad_): a
    # second pass over a program already holding grad vars must not
    # clobber the first pass's canonical @GRAD names — its canonicals
    # get an @<pass> suffix when the base name predates this pass
    prev = _PASS_STATE.copy()
    _PASS_STATE["times"] = program._appending_grad_times
    _PASS_STATE["preexisting"] = frozenset(
        n for b in program.blocks for n in b.vars)
    try:
        with program._backward_role_guard():
            return _append_backward_impl(loss, block, program,
                                         parameter_list, no_grad_set,
                                         checkpoints)
    finally:
        _PASS_STATE.clear()
        _PASS_STATE.update(prev)


_PASS_STATE: Dict = {}


def grad_name_for(n: str) -> str:
    """Canonical grad-var name for ``n`` in the CURRENT backward pass:
    the plain ``n@GRAD`` unless an earlier pass already owns it."""
    base = framework.grad_var_name(n)
    if _PASS_STATE.get("times", 1) > 1 \
            and base in _PASS_STATE.get("preexisting", ()):
        return "%s@%d" % (base, _PASS_STATE["times"])
    return base


def _emit_recompute_ops(block, path, checkpoints) -> Dict[str, str]:
    """Append renamed copies of the forward path ops (checkpoint vars and
    externally-produced vars are read as-is). Returns the old->new name
    map the grad binding uses for forward-value references."""
    keep = {c.name if hasattr(c, "name") else str(c) for c in checkpoints}
    rename: Dict[str, str] = {}
    for idx in path:
        op = block.ops[idx]
        outs_to_rename = [n for n in op.output_arg_names
                          if n and n not in keep]
        if not outs_to_rename:
            continue  # only checkpoint outputs: stored, not recomputed
        new_inputs = {slot: [rename.get(n, n) for n in names]
                      for slot, names in op.inputs.items()}
        new_outputs = {}
        for slot, names in op.outputs.items():
            outs = []
            for n in names:
                if not n:
                    outs.append(n)
                    continue
                # NEVER rebind the original name: checkpoint values are
                # stored (reads go to the original), and persistable
                # outputs (BN running stats) must not update twice.
                nn = n + "@RECOMPUTE"
                if nn not in block.vars:
                    v = block._find_var_recursive(n)
                    nv = block.create_var(
                        name=nn,
                        shape=None if v is None else v.shape,
                        dtype="float32" if v is None else v.dtype)
                    nv.stop_gradient = True
                if n not in keep:
                    rename[n] = nn
                outs.append(nn)
            new_outputs[slot] = outs
        attrs = dict(op.attrs)
        attrs.setdefault("_fwd_op_id", op._id or 0)
        block.append_op(op.type, inputs=new_inputs, outputs=new_outputs,
                        attrs=attrs, infer_shape=False)
    return rename


def _append_backward_impl(loss, block, program, parameter_list=None,
                          no_grad_set=None, checkpoints=None):

    no_grad = set()
    for b in program.blocks:
        for v in b.vars.values():
            if v.stop_gradient:
                no_grad.add(v.name)
    if no_grad_set:
        no_grad |= {n if isinstance(n, str) else n.name for n in no_grad_set}

    req = _requires_grad_set(block, parameter_list, no_grad)
    # propagate requires-grad forward through the op list
    diffable: Set[str] = set(req)
    for op in block.ops:
        info = _op_info(op.type)
        if info is None or info.grad is None and not _has_grad_op(op.type):
            continue
        if any(n in diffable for n in op.input_arg_names):
            for n in op.output_arg_names:
                if n not in no_grad:
                    diffable.add(n)

    path = _find_op_path(block, loss.name, req)

    # Recompute (reference backward.py:623
    # _append_backward_ops_with_checkpoints_): re-emit the forward ops of
    # each inter-checkpoint segment at the start of the backward region
    # with renamed outputs; grad ops then read the RECOMPUTED values, so
    # the original intermediates have no backward consumers and die
    # early. RNG ops re-emit with the original op's seed stream so
    # dropout masks match. (Under whole-program compilation XLA may CSE
    # a re-emitted op back onto its original when that is cheaper —
    # memory behavior is then the compiler's call, never worse.)
    recompute_rename: Dict[str, str] = {}
    if checkpoints:
        recompute_rename = _emit_recompute_ops(block, path, checkpoints)

    # Seed d(loss)/d(loss) = 1
    loss_grad_name = grad_name_for(loss.name)
    _ensure_grad_var(block, loss.name, loss_grad_name)
    block.append_op(
        "fill_constant",
        inputs={},
        outputs={"Out": loss_grad_name},
        attrs={
            "shape": list(loss.shape or ()),
            "value": 1.0,
            "dtype": _dtype_enum(loss.dtype),
            "force_cpu": False,
        },
        infer_shape=False,
    )

    # pending grads per forward var (producers merge on arrival)
    pending: Dict[str, List[str]] = {loss.name: [loss_grad_name]}
    grad_to_var: Dict[str, str] = {loss_grad_name: loss.name}

    def finalize(var_name: str) -> Optional[str]:
        """Merge pending partial grads of var into canonical var@GRAD."""
        glist = pending.get(var_name)
        if not glist:
            return None
        canonical = grad_name_for(var_name)
        if len(glist) == 1 and glist[0] == canonical:
            return canonical
        _ensure_grad_var(block, var_name, canonical)
        block.append_op(
            "sum",
            inputs={"X": list(glist)},
            outputs={"Out": canonical},
            infer_shape=False,
        )
        pending[var_name] = [canonical]
        return canonical

    for idx in reversed(path):
        op = block.ops[idx]
        info = _op_info(op.type)
        if info is None:
            continue
        grad_type = op.type + "_grad"
        # A callable grad maker owns its op's backward entirely (custom
        # output binding, e.g. data_norm's in-place stat rebind) — it wins
        # even when a <type>_grad op is also registered for it to emit.
        if callable(info.grad) and info.grad != "auto":
            info.grad(block, op, pending, finalize)
            continue
        if not _has_grad_op(op.type):
            # info.grad is None or "auto" with no grad op: grads don't flow
            continue
        ginfo = OpInfoMap.instance().get(grad_type)

        # which outputs have incoming grads?
        out_grads = {}
        has_grad = False
        for slot in info.outputs:
            names = op.output(slot.name)
            if not names:
                continue
            gnames = []
            for n in names:
                g = finalize(n)
                gnames.append(g if g is not None else "")
                if g is not None:
                    has_grad = True
            if any(gnames):
                out_grads[slot.name + GRAD_SUFFIX] = gnames
        if not has_grad:
            continue

        # bind inputs: forward ins + out grads. Forward VALUE references
        # go through the recompute rename (grad math reads recomputed
        # activations); grad accumulation stays on original names.
        g_inputs = {}
        for slot in info.inputs:
            names = op.input(slot.name)
            if names:
                g_inputs[slot.name] = [recompute_rename.get(n, n)
                                       for n in names]
        g_inputs.update(out_grads)
        # some custom grad ops consume forward outputs too (slot name match)
        for slot in ginfo.inputs:
            if slot.name in g_inputs or slot.name.endswith(GRAD_SUFFIX):
                continue
            if slot.name in op.outputs:
                g_inputs[slot.name] = [recompute_rename.get(n, n)
                                       for n in op.outputs[slot.name]]

        # outputs: a fresh partial-grad name per diffable input var.
        # no_grad forward slots (labels, masks) never get a grad binding —
        # the grad kernel won't write them, and binding one would leave an
        # uninitialized var feeding the downstream sum (ADVICE r1 #3).
        g_outputs = {}
        for slot in info.inputs:
            if slot.no_grad:
                continue
            names = op.input(slot.name)
            if not names:
                continue
            gnames = []
            bind = False
            for n in names:
                if n in diffable and n not in no_grad:
                    if n in pending and pending[n]:
                        gname = "%s@RENAME@%d" % (grad_name_for(n),
                                                  len(pending[n]))
                    else:
                        gname = grad_name_for(n)
                    _ensure_grad_var(block, n, gname)
                    pending.setdefault(n, []).append(gname)
                    grad_to_var[gname] = n
                    gnames.append(gname)
                    bind = True
                else:
                    gnames.append("")
            if bind:
                g_outputs[slot.name + GRAD_SUFFIX] = gnames

        if not g_outputs:
            continue

        g_attrs = dict(op.attrs)
        g_attrs["_fwd_op_id"] = op._id
        block.append_op(grad_type, g_inputs, g_outputs, g_attrs,
                        infer_shape=False)

    # finalize leaves (parameters & data): merge their partial grads
    params_and_grads = []
    target_params = (
        [p if isinstance(p, framework.Variable) else block.var(p)
         for p in parameter_list]
        if parameter_list is not None
        else block.program.all_parameters()
    )
    for p in target_params:
        g = finalize(p.name)
        if g is None:
            continue
        params_and_grads.append((p, block.var(g)))
    return params_and_grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """fluid.gradients (reference backward.py:1678): d(targets)/d(inputs)."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    assert len(targets) == 1, "multi-target gradients arrive with a later wave"
    loss = targets[0]
    block = loss.block
    pre_names = {v.name for v in inputs}
    append_backward(loss, parameter_list=[v.name for v in inputs]
                    if all(isinstance(v, framework.Variable) for v in inputs)
                    else None,
                    no_grad_set=no_grad_set)
    outs = []
    for v in inputs:
        gname = framework.grad_var_name(v.name)
        outs.append(block.var(gname) if block.has_var(gname) else None)
    return outs


def _op_info(op_type):
    try:
        return OpInfoMap.instance().get(op_type)
    except KeyError:
        return None


def _has_grad_op(op_type):
    if OpInfoMap.instance().has(op_type + "_grad"):
        return True
    # grad programs are differentiable too: auto-VJP grad ops get their
    # own grad op registered on demand (static double-grad — reference
    # conv2d_grad_grad / elementwise_*_grad_grad)
    return ensure_grad_op(op_type)


def _dtype_enum(dtype):
    from .core import dtypes as _dt

    return _dt.dtype_to_enum(dtype)
