"""Verifier-gated parallelism-plan search.

The search SPACE (the knobs an operator hand-picks today): the
dp/mp/pp/sp/ep factorization of the device count, the cross-replica
sharded update, the bucket layout (size cap or the PR-10 profile
replanner), the reduction-strategy spelling, per-bucket quantization
(+ EQuARX error feedback), and async start/await scheduling.

The search INVARIANT (the point of this subsystem): every candidate is
rewritten SYMBOLICALLY on a fresh program and gated through the PR-12
static analyses — ``verify_program`` + ``check_collective_schedule`` +
``check_cross_rank`` — before anything is ever traced or measured. A
candidate that fails verification is recorded and discarded; it can
never reach a compile, let alone a mesh. ``schedule_record`` digests
dedup equivalent candidates (e.g. a profile replan that reproduced the
size layout).

Shape: a two-stage beam. Stage A enumerates the structural space
(mesh x sharded-update x bucket layout), rewrites + verifies each, and
keeps the ``beam_width`` cheapest by the fitted cost model. Stage B
expands the survivors over (strategy x quant x async), rewrites +
verifies each expansion, dedups by (schedule digest, spelling), and
ranks. The winner serializes to a :class:`~.plan.PlacementPlan`.

Meshes whose non-dp axes the model was not BUILT for (no sharded
embedding / ring attention / MoE / pipeline metadata on the program)
are enumerated and recorded as ``unsupported`` — a post-hoc search
cannot retrofit a hybrid transpiler pass, it can only refuse loudly.
"""
from __future__ import annotations

import itertools
import json
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .cost_model import CostModel, fit_cost_model
from .plan import PlacementPlan

__all__ = ["search_placement", "enumerate_meshes", "model_capabilities",
           "Candidate"]

MESH_AXES = ("dp", "mp", "pp", "sp", "ep")


class Candidate:
    """One point of the search space + its audit trail."""

    __slots__ = ("mesh", "sharded_update", "bucket_plan", "bucket_mb",
                 "strategy", "quant_mode", "quant_buckets",
                 "error_feedback", "async_collectives", "status",
                 "predicted_step_ms", "provenance", "schedule_digest",
                 "error", "verified", "traced", "schedule")

    def __init__(self, mesh, sharded_update=False, bucket_plan="size",
                 bucket_mb=4.0, strategy="ring", quant_mode="none",
                 quant_buckets=None, error_feedback=False,
                 async_collectives=False):
        self.mesh = tuple(mesh)
        self.sharded_update = sharded_update
        self.bucket_plan = bucket_plan
        self.bucket_mb = bucket_mb
        self.strategy = strategy
        self.quant_mode = quant_mode
        self.quant_buckets = quant_buckets
        self.error_feedback = error_feedback
        self.async_collectives = async_collectives
        self.status = "enumerated"
        self.predicted_step_ms = None
        self.provenance = None
        self.schedule_digest = None
        self.error = None
        self.verified = False   # passed the full static gate
        # tripwire: the symbolic search never traces, so this stays
        # False everywhere today — but ANY future code that measures /
        # compiles a candidate MUST set it, or the audit's
        # traced_before_verify counter (and the CI gate asserting it
        # is zero) silently loses its teeth
        self.traced = False
        self.schedule = None    # the scored collective schedule

    def key(self) -> Tuple:
        return (self.mesh, self.sharded_update, self.bucket_plan,
                self.bucket_mb, self.strategy, self.quant_mode,
                tuple(self.quant_buckets or ()), self.error_feedback,
                self.async_collectives)

    def spawn(self, **overrides) -> "Candidate":
        kw = {"mesh": self.mesh, "sharded_update": self.sharded_update,
              "bucket_plan": self.bucket_plan,
              "bucket_mb": self.bucket_mb, "strategy": self.strategy,
              "quant_mode": self.quant_mode,
              "quant_buckets": self.quant_buckets,
              "error_feedback": self.error_feedback,
              "async_collectives": self.async_collectives}
        kw.update(overrides)
        return Candidate(**kw)

    def audit_row(self) -> Dict:
        return {
            "mesh": [[a, s] for a, s in self.mesh],
            "sharded_update": self.sharded_update,
            "bucket": {"plan": self.bucket_plan,
                       "bucket_mb": self.bucket_mb},
            "strategy": self.strategy,
            "quant": {"mode": self.quant_mode,
                      "buckets": self.quant_buckets,
                      "error_feedback": self.error_feedback},
            "async_collectives": self.async_collectives,
            "status": self.status,
            "verified": self.verified,
            "traced": self.traced,
            "predicted_step_ms": self.predicted_step_ms,
            "provenance": self.provenance,
            "schedule_digest": self.schedule_digest,
            "error": self.error,
        }


# ---------------------------------------------------------------------------
# mesh enumeration
# ---------------------------------------------------------------------------


def model_capabilities(program) -> frozenset:
    """Mesh axes the BUILT program can actually use: dp always; a
    hybrid axis only when the build-time transpiler pass left its
    metadata on the program (shard specs / data axes / pipeline
    stages). A factorization needing anything else is unsupported for
    this model — recorded, not guessed at."""
    caps = {"dp"}
    specs = getattr(program, "_var_shard_specs", None) or {}
    data_axes = set(getattr(program, "_data_axes", None) or ())
    for spec in specs.values():
        caps.update(a for a in (spec or ()) if a)
    caps.update(a for a in data_axes if a)
    if getattr(program, "_pipeline_cuts", None) is not None or \
            getattr(program, "_pipeline_stages", None) is not None:
        caps.add("pp")
    return frozenset(caps & set(MESH_AXES))


def _factor_splits(n: int, k: int):
    """All ordered k-tuples of ints >= 1 whose product is n."""
    if k == 1:
        yield (n,)
        return
    for d in range(1, n + 1):
        if n % d:
            continue
        for rest in _factor_splits(n // d, k - 1):
            yield (d,) + rest


def enumerate_meshes(n_devices: int, caps: frozenset
                     ) -> Tuple[List[Tuple], List[Dict]]:
    """(supported, unsupported) mesh factorizations of ``n_devices``
    over dp/mp/pp/sp/ep. A mesh is the tuple of (axis, size) with
    size > 1 axes kept in canonical order (plus pure-dp as
    ``(("dp", n),)``). Unsupported rows carry the missing axes."""
    supported: List[Tuple] = []
    unsupported: List[Dict] = []
    seen = set()
    for sizes in _factor_splits(int(n_devices), len(MESH_AXES)):
        mesh = tuple((a, s) for a, s in zip(MESH_AXES, sizes) if s > 1)
        if not mesh:
            mesh = (("dp", int(n_devices)),)
        if mesh in seen:
            continue
        seen.add(mesh)
        missing = sorted({a for a, s in mesh if s > 1} - set(caps))
        if missing:
            unsupported.append({
                "mesh": [[a, s] for a, s in mesh],
                "status": "unsupported",
                "error": "model was not built for axes %s (no "
                         "build-time transpiler metadata)" % missing})
        else:
            supported.append(mesh)
    return supported, unsupported


# ---------------------------------------------------------------------------
# symbolic rewrite + static gate
# ---------------------------------------------------------------------------


def _rewrite_candidate(cand: Candidate, builder: Callable, report):
    """Build a FRESH program and apply the candidate's rewrite stack —
    exactly the passes ``maybe_rewrite_collectives`` would run under
    this plan. Returns (program, scope, loss_name). Symbolic only:
    nothing here touches a device."""
    from ..core.scope import Scope
    from ..parallel.collectives import (apply_sharded_weight_update,
                                        bucket_allreduce_ops)
    from ..parallel.scheduling import (configure_bucket_quant,
                                       schedule_async_collectives,
                                       swap_reduction_strategy)
    from ..parallel.transpiler import insert_allreduce_ops

    main, loss_name = builder()
    scope = Scope()
    nranks = 1
    for _a, s in cand.mesh:
        nranks *= s
    data_axis = cand.mesh[0][0]
    insert_allreduce_ops(main, nranks)
    if cand.sharded_update:
        apply_sharded_weight_update(main, scope, nranks, axis=data_axis,
                                    quant=cand.quant_mode)
    bucket_allreduce_ops(
        main, bucket_bytes=int(cand.bucket_mb * (1 << 20)),
        quant=cand.quant_mode, scope=scope,
        plan=cand.bucket_plan,
        report=report if cand.bucket_plan == "profile" else None)
    if cand.strategy != "ring":
        swap_reduction_strategy(main, cand.strategy)
    if cand.error_feedback or cand.quant_buckets:
        configure_bucket_quant(main, scope, nranks, data_axis,
                               modes=cand.quant_buckets,
                               error_feedback=cand.error_feedback,
                               materialize=False)
    if cand.async_collectives:
        # the report gates splits by measured slack REGARDLESS of the
        # bucket plan — the engine passes the plan's embedded report
        # the same way, so the candidate verified+priced here is the
        # schedule that actually executes
        schedule_async_collectives(main, report=report, scope=scope)
    return main, scope, loss_name


def _static_gate(cand: Candidate, program, scope, loss_name,
                 nranks: int) -> Dict:
    """The PR-12 gate, in full: well-formedness, single-program
    collective-schedule safety, and the cross-rank comparison (under
    SPMD every rank traces this same program — the pairwise check is
    run on the extracted schedule per rank so a rank-divergence bug in
    the EXTRACTION itself would also surface). Raises on any error
    finding; returns the schedule record (ok + digest)."""
    from ..analysis import (check_collective_schedule, check_cross_rank,
                            schedule_record, verify_program)

    verify_program(program, fetch_names=[loss_name],
                   pass_name="placement_search")
    sigs = check_collective_schedule(program, nranks=nranks,
                                     where="placement_search",
                                     scope=scope)
    check_cross_rank([list(sigs) for _ in range(min(nranks, 2))],
                     where="placement_search", scope=scope)
    return schedule_record(program, nranks=nranks, scope=scope)


def _candidate_schedule(program, scope) -> List[Dict]:
    """The cost-model view of a rewritten program's collectives:
    kind / executed bytes / availability position / strategy, via the
    same ``build_phase_plan`` the profiler measures with."""
    from ..observability.profiler import build_phase_plan

    plan = build_phase_plan(program, state=scope)
    return [{"op": c["type"], "kind": c["kind"], "bytes": c["bytes"],
             "avail_pos": c["avail_pos"],
             "strategy": c.get("strategy", "ring"),
             "quant": c.get("quant", "none")}
            for c in plan["collectives"]]


def _score(cand: Candidate, builder: Callable, report,
           model: CostModel) -> Optional[Tuple]:
    """Rewrite + gate + price one candidate. Mutates the candidate's
    audit fields; returns (program-free) ranking tuple or None when
    the candidate was rejected."""
    nranks = 1
    for _a, s in cand.mesh:
        nranks *= s
    try:
        program, scope, loss_name = _rewrite_candidate(cand, builder,
                                                       report)
    except Exception as e:  # a model/bucket mismatch, not a verdict
        cand.status = "rejected"
        cand.error = "rewrite failed: %r" % (e,)
        return None
    try:
        rec = _static_gate(cand, program, scope, loss_name, nranks)
    except Exception as e:
        cand.status = "rejected"
        cand.error = "static gate: %s" % str(e)[:500]
        return None
    cand.verified = True
    cand.schedule_digest = rec.get("digest")
    sched = _candidate_schedule(program, scope)
    stage_sizes = [s for _a, s in cand.mesh if s > 1]
    for c in sched:
        c["stage_sizes"] = stage_sizes
    cand.schedule = sched
    pred = model.predict(sched,
                         async_scheduled=cand.async_collectives)
    cand.predicted_step_ms = pred["step_ms"]
    cand.provenance = pred["provenance"]
    cand.status = "verified"
    return (pred["step_ms"], json.dumps(cand.audit_row()["quant"],
                                        sort_keys=True), cand.key())


def derive_quant_buckets(schedule, model) -> Optional[List[str]]:
    """Per-bucket quantization: for each bucket op in the scored
    schedule, pick the wire mode the cost model prices cheapest at
    that bucket's payload (executed widths + the unmeasured-mode
    compute penalty — so on the emulated wire this honestly derives
    all-"none", and flips wire-bound buckets only once fitted terms
    say the wire dominates). Returns one mode per bucket op, or None
    when nothing would quantize (the uniform candidate covers it)."""
    from ..ops.collective_ops import QUANT_PSUM_ITEMSIZE

    ents = [c for c in (schedule or ())
            if c.get("op") in ("c_bucket_allreduce",
                               "c_bucket_allreduce_start")]
    if not ents:
        return None
    modes: List[str] = []
    for c in ents:
        best, best_ms = "none", None
        for m in ("none", "bf16", "int8"):
            scale = (QUANT_PSUM_ITEMSIZE.get(m) or 4) / 4.0
            ms = model.collective_ms(c["kind"],
                                     float(c["bytes"]) * scale,
                                     c.get("strategy", "ring"),
                                     c.get("stage_sizes"), quant=m)
            if best_ms is None or ms < best_ms - 1e-12:
                best, best_ms = m, ms
        modes.append(best)
    if all(m == "none" for m in modes):
        return None
    return modes


# ---------------------------------------------------------------------------
# the beam
# ---------------------------------------------------------------------------


def _dedup_key(cand: Candidate) -> Tuple:
    """Two candidates whose rewritten programs carry the same schedule
    digest AND the same spelling knobs are the same plan (the typical
    hit: a profile replan that reproduced the size layout)."""
    return (cand.schedule_digest, cand.strategy, cand.quant_mode,
            tuple(cand.quant_buckets or ()), cand.error_feedback,
            cand.async_collectives)


def search_placement(builder: Callable, n_devices: int,
                     report: Optional[Dict] = None, beam_width: int = 4,
                     seed: int = 0, model: str = "",
                     strategies: Optional[Sequence[str]] = None,
                     include_quant: bool = True) -> Tuple[
                         Optional[PlacementPlan], Dict]:
    """Search the plan space for ``builder``'s model on ``n_devices``.

    ``builder() -> (main_program, loss_name)`` must return a FRESH
    un-transpiled training program each call (the search rewrites them
    destructively). Returns ``(winning_plan | None, audit)`` — the
    audit carries one row per enumerated candidate plus the
    enumeration/dedup/prune accounting the CI gate asserts over.
    Deterministic: same builder + report + seed => same winner digest
    (the search itself draws no randomness; ``seed`` is recorded so a
    future stochastic refinement stays pinned)."""
    from ..observability import steering

    report = steering.coerce_report(report) if report is not None \
        else None
    cost = fit_cost_model(report, nranks=n_devices)

    probe, _loss = builder()
    caps = model_capabilities(probe)
    meshes, unsupported = enumerate_meshes(n_devices, caps)

    # -- stage A: structural beam (mesh x sharded x bucket layout) ----------
    bucket_dims: List[Tuple[str, float]] = [("size", 4.0), ("size", 1.0)]
    if report is not None:
        bucket_dims.append(("profile", 4.0))
    stage_a: List[Candidate] = []
    for mesh, sharded in itertools.product(meshes, (False, True)):
        if sharded:
            # bucket layout is moot once the update is sharded (the
            # grads collapse into the fused op) — one candidate
            stage_a.append(Candidate(mesh, sharded_update=True))
        else:
            for bplan, mb in bucket_dims:
                stage_a.append(Candidate(mesh, bucket_plan=bplan,
                                         bucket_mb=mb))
    all_rows: List[Candidate] = list(stage_a)
    ranked_a = []
    for cand in stage_a:
        rank = _score(cand, builder, report, cost)
        if rank is not None:
            ranked_a.append((rank, cand))
    ranked_a.sort(key=lambda rc: rc[0])
    survivors = [c for _r, c in ranked_a[:max(1, int(beam_width))]]
    for _r, c in ranked_a[max(1, int(beam_width)):]:
        c.status = "pruned"   # verified but beam-cut before expansion

    # -- stage B: spelling expansion (strategy x quant x async) -------------
    strategies = tuple(strategies or ("ring", "tree", "two_stage"))
    seen: Dict[Tuple, Candidate] = {}
    ranked_b = []
    for base in survivors:
        n_multi_axes = sum(1 for _a, s in base.mesh if s > 1)
        for strat in strategies:
            if strat == "two_stage" and n_multi_axes < 2:
                continue  # degenerates to ring on a 1-axis mesh
            if base.sharded_update and strat != "ring":
                continue  # the fused update op keeps its own psum
            quants: List[Tuple] = [("none", None, False)]
            if include_quant and not base.sharded_update:
                quants += [("bf16", None, False), ("int8", None, True)]
                # per-bucket derivation: the cost model flips each
                # wire-bound bucket individually (EF rides along when
                # any bucket goes int8)
                derived = derive_quant_buckets(base.schedule, cost)
                if derived is not None:
                    quants.append(("none", derived,
                                   "int8" in derived))
            for qmode, qbuckets, ef in quants:
                for use_async in ((False,) if base.sharded_update
                                  else (False, True)):
                    if (strat, qmode, qbuckets, ef, use_async) == \
                            ("ring", "none", None, False, False):
                        cand = base  # already scored in stage A
                    else:
                        cand = base.spawn(strategy=strat,
                                          quant_mode=qmode,
                                          quant_buckets=qbuckets,
                                          error_feedback=ef,
                                          async_collectives=use_async)
                        all_rows.append(cand)
                        if _score(cand, builder, report, cost) is None:
                            continue
                    dk = _dedup_key(cand)
                    prev = seen.get(dk)
                    if prev is not None:
                        if cand is not prev:
                            cand.status = "deduped"
                        continue
                    seen[dk] = cand
                    ranked_b.append(
                        ((cand.predicted_step_ms,
                          json.dumps([[a, s] for a, s in cand.mesh]),
                          repr(cand.key())), cand))
    ranked_b.sort(key=lambda rc: rc[0])

    audit = {
        "schema": "placement_search_audit_v1",
        "model": model,
        "n_devices": int(n_devices),
        "seed": int(seed),
        "beam_width": int(beam_width),
        "capabilities": sorted(caps),
        "cost_provenance": cost.provenance,
        "report_used": report is not None,
        "enumerated": len(all_rows) + len(unsupported),
        "verified": sum(1 for c in all_rows if c.verified),
        "rejected": sum(1 for c in all_rows
                        if c.status == "rejected"),
        "deduped": sum(1 for c in all_rows if c.status == "deduped"),
        "pruned": sum(1 for c in all_rows if c.status == "pruned"),
        "traced_before_verify": sum(
            1 for c in all_rows if c.traced and not c.verified),
        "unsupported": unsupported,
        "candidates": [c.audit_row() for c in all_rows],
    }
    from .. import observability as _obs

    _obs.inc("placement.candidates", len(all_rows))
    _obs.inc("placement.candidates_verified", audit["verified"])

    if not ranked_b:
        return None, audit
    best = ranked_b[0][1]
    best.status = "winner"
    audit["winner"] = best.audit_row()
    plan = PlacementPlan(
        mesh=best.mesh, strategy=best.strategy,
        bucket_mb=best.bucket_mb, bucket_plan_mode=best.bucket_plan,
        quant_mode=best.quant_mode, quant_buckets=best.quant_buckets,
        error_feedback=best.error_feedback,
        sharded_update=best.sharded_update,
        async_collectives=best.async_collectives,
        report=report,  # embedded: the artifact is self-contained
        predicted_step_ms=best.predicted_step_ms,
        cost_provenance=best.provenance or cost.provenance,
        schedule_digest=best.schedule_digest or "", model=model,
        source={"seed": int(seed), "beam_width": int(beam_width),
                "n_devices": int(n_devices),
                "enumerated": audit["enumerated"],
                "verified": audit["verified"]})
    return plan, audit


# -- steering registration ---------------------------------------------------


def _steer_placement(report, builder=None, n_devices=None, **ctx):
    """``steer("placement", report, builder=..., n_devices=...)`` —
    the report→plan entry the ROADMAP's steering interface names; the
    placement CLI and tests dispatch through it."""
    if builder is None or n_devices is None:
        raise ValueError("placement steerer needs builder= and "
                         "n_devices=")
    return search_placement(builder, n_devices, report=report, **ctx)


from ..observability import steering as _steering  # noqa: E402

_steering.register_steerer(
    "placement", _steer_placement,
    "verifier-gated parallelism-plan search (ISSUE 15)")
