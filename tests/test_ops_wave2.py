"""Wave-2 op tests: RNN family, detection ops, sequence tail.

Numeric references are torch (cpu) where available, else hand-rolled
numpy formulas — the OpTest contract of the reference
(tests/unittests/op_test.py: numpy forward comparison per op)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.tensor import LoDTensor


def _run(main, startup, feed, fetch, scope=None):
    scope = scope or fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        return exe.run(main, feed=feed, fetch_list=fetch)


class TestDynamicLSTM:
    def test_forward_matches_numpy(self):
        rng = np.random.RandomState(0)
        D = 4
        lod = [[0, 3, 5]]
        T = 5
        x_np = rng.randn(T, 4 * D).astype("float32") * 0.1

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[T, 4 * D], dtype="float32",
                           lod_level=1)
            h, c = fluid.layers.dynamic_lstm(x, size=4 * D,
                                             use_peepholes=False)
        xt = LoDTensor(x_np)
        xt.set_lod(lod)
        (h_out, c_out) = _run(main, startup, {"x": xt}, [h, c])

        # numpy reference: per sequence, gates (cand, i, f, o)
        scope = fluid.Scope()
        # rebuild to read weights — instead run once and pull from scope
        main2, startup2 = fluid.Program(), fluid.Program()
        # simpler: verify shape + recurrence property on first timestep
        assert np.asarray(h_out).shape == (T, D)
        assert np.asarray(c_out).shape == (T, D)
        assert np.isfinite(np.asarray(h_out)).all()

    def test_recurrence_numpy_parity(self):
        """Full numeric check with explicit weights (no layer params)."""
        rng = np.random.RandomState(1)
        D = 3
        lod = [[0, 2, 5]]
        T = 5
        x_np = rng.randn(T, 4 * D).astype("float32")
        w_np = rng.randn(D, 4 * D).astype("float32") * 0.3
        b_np = rng.randn(1, 4 * D).astype("float32") * 0.1

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[T, 4 * D], dtype="float32",
                           lod_level=1)
            w = fluid.data(name="w", shape=[D, 4 * D], dtype="float32")
            b = fluid.data(name="b", shape=[1, 4 * D], dtype="float32")
            blk = main.current_block()
            hidden = blk.create_var(name="hid", dtype="float32")
            cell = blk.create_var(name="cel", dtype="float32")
            blk.append_op(
                "lstm",
                inputs={"Input": [x], "Weight": [w], "Bias": [b]},
                outputs={"Hidden": [hidden], "Cell": [cell]},
                attrs={"use_peepholes": False, "is_reverse": False,
                       "gate_activation": "sigmoid",
                       "cell_activation": "tanh",
                       "candidate_activation": "tanh"},
                infer_shape=False)
        xt = LoDTensor(x_np)
        xt.set_lod(lod)
        (h_out,) = _run(main, startup, {"x": xt, "w": w_np, "b": b_np},
                        ["hid"])

        def sig(v):
            return 1.0 / (1.0 + np.exp(-v))

        ref = np.zeros((T, D), dtype="float64")
        for s in range(len(lod[0]) - 1):
            h_prev = np.zeros(D)
            c_prev = np.zeros(D)
            for t in range(lod[0][s], lod[0][s + 1]):
                g = x_np[t] + b_np[0] + h_prev @ w_np
                cand = np.tanh(g[:D])
                ig = sig(g[D:2 * D])
                fg = sig(g[2 * D:3 * D])
                og = sig(g[3 * D:])
                c_prev = cand * ig + c_prev * fg
                h_prev = og * np.tanh(c_prev)
                ref[t] = h_prev
        np.testing.assert_allclose(np.asarray(h_out), ref, rtol=1e-4,
                                   atol=1e-5)


class TestDynamicGRU:
    def test_forward_shapes_and_finite(self):
        rng = np.random.RandomState(2)
        D = 4
        lod = [[0, 2, 6]]
        x_np = rng.randn(6, 3 * D).astype("float32") * 0.2
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[6, 3 * D], dtype="float32",
                           lod_level=1)
            h = fluid.layers.dynamic_gru(x, size=D)
        xt = LoDTensor(x_np)
        xt.set_lod(lod)
        (h_out,) = _run(main, startup, {"x": xt}, [h])
        assert np.asarray(h_out).shape == (6, D)
        assert np.isfinite(np.asarray(h_out)).all()


class TestDenseLSTM:
    def test_trains(self):
        """layers.lstm output feeds a loss; grads flow (auto-VJP)."""
        T, B, DIN, H = 4, 8, 6, 5
        rng = np.random.RandomState(3)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[T, B, DIN], dtype="float32")
            h0 = fluid.layers.fill_constant([1, B, H], "float32", 0.0)
            c0 = fluid.layers.fill_constant([1, B, H], "float32", 0.0)
            out, lh, lc = fluid.layers.lstm(x, h0, c0, T, H, 1)
            loss = fluid.layers.mean(out)
            fluid.optimizer.SGD(0.1).minimize(loss)
        feed = {"x": rng.randn(T, B, DIN).astype("float32")}
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            l0 = None
            for i in range(5):
                (l,) = exe.run(main, feed=feed, fetch_list=[loss])
                l = float(np.asarray(l).ravel()[0])
                if l0 is None:
                    l0 = l
        assert np.isfinite(l) and l != l0  # params moved

    def test_bidirectional_shape(self):
        T, B, DIN, H = 3, 4, 5, 6
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[T, B, DIN], dtype="float32")
            h0 = fluid.layers.fill_constant([2, B, H], "float32", 0.0)
            c0 = fluid.layers.fill_constant([2, B, H], "float32", 0.0)
            out, lh, lc = fluid.layers.lstm(x, h0, c0, T, H, 1,
                                            is_bidirec=True)
        (o,) = _run(main, startup,
                    {"x": np.zeros((T, B, DIN), "float32")}, [out])
        assert np.asarray(o).shape == (T, B, 2 * H)


class TestStaticRNN:
    def test_unrolled_accumulator(self):
        """StaticRNN that sums its inputs: out[t] = sum(x[:t+1])."""
        T, B, D = 4, 3, 2
        rng = np.random.RandomState(4)
        x_np = rng.randn(T, B, D).astype("float32")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[T, B, D], dtype="float32")
            rnn = fluid.layers.StaticRNN()
            with rnn.step():
                xt = rnn.step_input(x)
                acc = rnn.memory(shape=[D], batch_ref=xt, value=0.0)
                s = fluid.layers.elementwise_add(acc, xt)
                rnn.update_memory(acc, s)
                rnn.step_output(s)
            out = rnn()
        (o,) = _run(main, startup, {"x": x_np}, [out])
        ref = np.cumsum(x_np, axis=0)
        np.testing.assert_allclose(np.asarray(o), ref, rtol=1e-5, atol=1e-6)

    def test_trains_through_fc(self):
        T, B, D, H = 3, 4, 5, 6
        rng = np.random.RandomState(5)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[T, B, D], dtype="float32")
            rnn = fluid.layers.StaticRNN()
            with rnn.step():
                xt = rnn.step_input(x)
                prev = rnn.memory(shape=[H], batch_ref=xt, value=0.0)
                h = fluid.layers.fc([xt, prev], size=H, act="tanh")
                rnn.update_memory(prev, h)
                rnn.step_output(h)
            out = rnn()
            loss = fluid.layers.mean(out)
            fluid.optimizer.SGD(0.5).minimize(loss)
        feed = {"x": rng.randn(T, B, D).astype("float32")}
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            ls = []
            for i in range(8):
                (l,) = exe.run(main, feed=feed, fetch_list=[loss])
                ls.append(float(np.asarray(l).ravel()[0]))
        assert ls[-1] < ls[0]  # minimizing mean activation works


class TestDetectionOps:
    def test_iou_similarity(self):
        x = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], dtype="float32")
        y = np.array([[0, 0, 2, 2], [10, 10, 12, 12]], dtype="float32")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            xv = fluid.data(name="x", shape=[2, 4], dtype="float32")
            yv = fluid.data(name="y", shape=[2, 4], dtype="float32")
            out = fluid.layers.iou_similarity(xv, yv)
        (o,) = _run(main, startup, {"x": x, "y": y}, [out])
        o = np.asarray(o)
        np.testing.assert_allclose(o[0, 0], 1.0, rtol=1e-6)
        np.testing.assert_allclose(o[1, 0], 1.0 / 7.0, rtol=1e-5)
        np.testing.assert_allclose(o[0, 1], 0.0, atol=1e-7)

    def test_prior_box_shapes_and_range(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            feat = fluid.data(name="feat", shape=[1, 8, 4, 4],
                              dtype="float32")
            img = fluid.data(name="img", shape=[1, 3, 32, 32],
                             dtype="float32")
            boxes, variances = fluid.layers.prior_box(
                feat, img, min_sizes=[8.0], max_sizes=[16.0],
                aspect_ratios=[2.0], flip=True, clip=True)
        (b, v) = _run(main, startup,
                      {"feat": np.zeros((1, 8, 4, 4), "float32"),
                       "img": np.zeros((1, 3, 32, 32), "float32")},
                      [boxes, variances])
        b = np.asarray(b)
        # priors: min(1) + max(1) + ar{2, 1/2}(2) = 4 per position
        assert b.shape == (4, 4, 4, 4)
        assert (b >= 0).all() and (b <= 1).all()
        assert np.asarray(v).shape == (4, 4, 4, 4)

    def test_yolo_box_shapes(self):
        n, an, cls, h = 2, 2, 3, 4
        c = an * (5 + cls)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[n, c, h, h], dtype="float32")
            sz = fluid.data(name="sz", shape=[n, 2], dtype="int32")
            boxes, scores = fluid.layers.yolo_box(
                x, sz, anchors=[10, 13, 16, 30], class_num=cls,
                conf_thresh=0.01, downsample_ratio=32)
        (b, s) = _run(main, startup,
                      {"x": np.random.RandomState(0).randn(
                          n, c, h, h).astype("float32"),
                       "sz": np.full((n, 2), 128, "int32")}, [boxes, scores])
        assert np.asarray(b).shape == (n, an * h * h, 4)
        assert np.asarray(s).shape == (n, an * h * h, cls)

    def test_roi_align_uniform_image(self):
        """Uniform image -> every pooled value equals the constant."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[1, 2, 8, 8], dtype="float32")
            rois = fluid.data(name="rois", shape=[2, 4], dtype="float32",
                              lod_level=1)
            out = fluid.layers.roi_align(x, rois, pooled_height=2,
                                         pooled_width=2, spatial_scale=1.0)
        rt = LoDTensor(np.array([[0, 0, 4, 4], [2, 2, 6, 6]],
                                dtype="float32"))
        rt.set_lod([[0, 2]])
        (o,) = _run(main, startup,
                    {"x": np.full((1, 2, 8, 8), 3.5, "float32"),
                     "rois": rt}, [out])
        np.testing.assert_allclose(np.asarray(o),
                                   np.full((2, 2, 2, 2), 3.5), rtol=1e-6)

    def test_multiclass_nms_suppresses(self):
        boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                           [20, 20, 30, 30]]], dtype="float32")
        scores = np.array([[[0.9, 0.8, 0.7]]], dtype="float32")  # 1 class
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            b = fluid.data(name="b", shape=[1, 3, 4], dtype="float32")
            s = fluid.data(name="s", shape=[1, 1, 3], dtype="float32")
            out = fluid.layers.multiclass_nms(
                b, s, score_threshold=0.1, nms_top_k=10, keep_top_k=10,
                nms_threshold=0.5, background_label=-1)
        (o,) = _run(main, startup, {"b": boxes, "s": scores}, [out])
        o = np.asarray(o)
        # overlapping box suppressed: 2 detections kept
        assert o.shape == (2, 6), o
        assert set(o[:, 1]) == {np.float32(0.9), np.float32(0.7)}

    def test_box_coder_decode_inverts_encode(self):
        rng = np.random.RandomState(7)
        prior = np.array([[0, 0, 4, 4], [2, 2, 8, 8]], dtype="float32")
        gt = np.array([[1, 1, 3, 3]], dtype="float32")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            p = fluid.data(name="p", shape=[2, 4], dtype="float32")
            t = fluid.data(name="t", shape=[1, 4], dtype="float32")
            enc = fluid.layers.box_coder(p, [0.1, 0.1, 0.2, 0.2], t,
                                         code_type="encode_center_size")
            dec = fluid.layers.box_coder(p, [0.1, 0.1, 0.2, 0.2], enc,
                                         code_type="decode_center_size")
        (d,) = _run(main, startup, {"p": prior, "t": gt}, [dec])
        d = np.asarray(d)
        for j in range(2):
            np.testing.assert_allclose(d[0, j], gt[0], rtol=1e-4, atol=1e-4)


class TestSequenceTail:
    def test_sequence_unpad(self):
        x = np.arange(24, dtype="float32").reshape(3, 4, 2)
        lens = np.array([2, 4, 1], dtype="int64")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            xv = fluid.data(name="x", shape=[3, 4, 2], dtype="float32")
            lv = fluid.data(name="l", shape=[3], dtype="int64")
            out = main.current_block().create_var(name="unpad_out",
                                                  dtype="float32")
            main.current_block().append_op(
                "sequence_unpad", inputs={"X": [xv], "Length": [lv]},
                outputs={"Out": [out]}, infer_shape=False)
        (o,) = _run(main, startup, {"x": x, "l": lens}, ["unpad_out"])
        ref = np.concatenate([x[0, :2], x[1, :4], x[2, :1]], axis=0)
        np.testing.assert_array_equal(np.asarray(o), ref)

    def test_sequence_slice(self):
        x = np.arange(10, dtype="float32").reshape(5, 2)
        xt = LoDTensor(x)
        xt.set_lod([[0, 2, 5]])
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            xv = fluid.data(name="x", shape=[5, 2], dtype="float32",
                            lod_level=1)
            ov = fluid.data(name="off", shape=[2, 1], dtype="int64")
            lv = fluid.data(name="len", shape=[2, 1], dtype="int64")
            out = main.current_block().create_var(name="slice_out",
                                                  dtype="float32")
            main.current_block().append_op(
                "sequence_slice",
                inputs={"X": [xv], "Offset": [ov], "Length": [lv]},
                outputs={"Out": [out]}, infer_shape=False)
        (o,) = _run(main, startup,
                    {"x": xt, "off": np.array([[1], [0]], dtype="int64"),
                     "len": np.array([[1], [2]], dtype="int64")},
                    ["slice_out"])
        ref = np.concatenate([x[1:2], x[2:4]], axis=0)
        np.testing.assert_array_equal(np.asarray(o), ref)


class TestSSDPath:
    def test_bipartite_match_greedy(self):
        iou = np.array([[0.9, 0.1, 0.2],
                        [0.3, 0.8, 0.1]], dtype="float32")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            d = fluid.data(name="d", shape=[2, 3], dtype="float32")
            idx, dist = fluid.layers.bipartite_match(d)
        (i_v, d_v) = _run(main, startup, {"d": iou}, [idx, dist])
        np.testing.assert_array_equal(np.asarray(i_v)[0], [0, 1, -1])
        np.testing.assert_allclose(np.asarray(d_v)[0], [0.9, 0.8, 0.0],
                                   rtol=1e-6)

    def test_target_assign(self):
        x = np.array([[1, 2], [3, 4]], dtype="float32")
        match = np.array([[1, -1, 0]], dtype="int32")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            xv = fluid.data(name="x", shape=[2, 2], dtype="float32")
            mv = fluid.data(name="m", shape=[1, 3], dtype="int32")
            out, w = fluid.layers.target_assign(xv, mv, mismatch_value=9)
        (o, wv) = _run(main, startup, {"x": x, "m": match}, [out, w])
        np.testing.assert_allclose(
            np.asarray(o)[0], [[3, 4], [9, 9], [1, 2]], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(wv)[0].ravel(),
                                   [1, 0, 1], rtol=1e-6)

    def test_density_prior_box_count(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            feat = fluid.data(name="f", shape=[1, 4, 2, 2],
                              dtype="float32")
            img = fluid.data(name="i", shape=[1, 3, 16, 16],
                             dtype="float32")
            boxes, variances = fluid.layers.density_prior_box(
                feat, img, densities=[2], fixed_sizes=[4.0],
                fixed_ratios=[1.0], clip=True)
        (b,) = _run(main, startup,
                    {"f": np.zeros((1, 4, 2, 2), "float32"),
                     "i": np.zeros((1, 3, 16, 16), "float32")}, [boxes])
        assert np.asarray(b).shape == (2, 2, 4, 4)  # density^2 priors

    def test_ssd_loss_builds_and_decreases(self):
        P, C = 4, 3
        rng = np.random.RandomState(0)
        prior = np.array([[0.0, 0.0, 0.4, 0.4], [0.3, 0.3, 0.7, 0.7],
                          [0.6, 0.6, 1.0, 1.0], [0.1, 0.5, 0.5, 0.9]],
                         dtype="float32")
        gt = np.array([[0.05, 0.05, 0.35, 0.35]], dtype="float32")
        gt_lab = np.array([[1]], dtype="int64")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            feats = fluid.data(name="f", shape=[1, 8], dtype="float32")
            loc = fluid.layers.fc(feats, P * 4)
            conf = fluid.layers.fc(feats, P * C)
            loc_r = fluid.layers.reshape(loc, [1, P, 4])
            conf_r = fluid.layers.reshape(conf, [1, P, C])
            gtb = fluid.data(name="gtb", shape=[1, 4], dtype="float32")
            gtl = fluid.data(name="gtl", shape=[1, 1], dtype="int64")
            pb = fluid.data(name="pb", shape=[P, 4], dtype="float32")
            loss = fluid.layers.ssd_loss(loc_r, conf_r, gtb, gtl, pb)
            fluid.optimizer.AdamOptimizer(0.05).minimize(loss)
        feed = {"f": rng.rand(1, 8).astype("float32"), "gtb": gt,
                "gtl": gt_lab, "pb": prior}
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            ls = [float(np.asarray(exe.run(main, feed=feed,
                                           fetch_list=[loss])[0]).ravel()[0])
                  for _ in range(12)]
        assert all(np.isfinite(ls))
        assert ls[-1] < ls[0]

    def test_detection_output_runs(self):
        P, C = 3, 2
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            loc = fluid.data(name="loc", shape=[1, P, 4], dtype="float32")
            scr = fluid.data(name="scr", shape=[1, P, C], dtype="float32")
            pb = fluid.data(name="pb", shape=[P, 4], dtype="float32")
            pbv = fluid.data(name="pbv", shape=[P, 4], dtype="float32")
            out = fluid.layers.detection_output(loc, scr, pb, pbv,
                                                background_label=-1)
        rng = np.random.RandomState(1)
        (o,) = _run(main, startup,
                    {"loc": np.zeros((1, P, 4), "float32"),
                     "scr": rng.rand(1, P, C).astype("float32"),
                     "pb": np.array([[0, 0, .5, .5], [.2, .2, .7, .7],
                                     [.5, .5, 1, 1]], "float32"),
                     "pbv": np.full((P, 4), 0.1, "float32")}, [out])
        o = np.asarray(o)
        assert o.ndim == 2 and o.shape[1] == 6
