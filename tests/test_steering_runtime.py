"""ISSUE 16: the self-driving runtime — sampled capture knob, steering
registry edge cases, daemon hysteresis (no replan storm), the extracted
comparator, and the canary/audit closure.

End-to-end (real executor job under PADDLE_TPU_SAMPLE_EVERY, planted
regression/improvement canaries) lives in ``tools/steering_drill.py``;
these tests pin the unit contracts the drill composes."""
import json
import os
import socket

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.observability import canary as canary_mod
from paddle_tpu.observability import capture as capture_mod
from paddle_tpu.observability import comparator as comp_mod
from paddle_tpu.observability import distributed as odist
from paddle_tpu.observability import flight
from paddle_tpu.observability import steering
from paddle_tpu.observability import steering_daemon as sd_mod
from paddle_tpu.observability import timeseries as ts_mod


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_SAMPLE_EVERY", raising=False)
    monkeypatch.delenv("PADDLE_TPU_METRICS_DIR", raising=False)
    monkeypatch.delenv("PADDLE_TPU_TIMESERIES", raising=False)
    monkeypatch.delenv("PADDLE_TPU_TIMESERIES_WINDOWS", raising=False)
    monkeypatch.delenv("PADDLE_TPU_AB_PAIRS", raising=False)
    obs.reset()
    obs.enable()
    flight.clear()
    capture_mod._reset_for_tests()
    ts_mod._reset_for_tests()
    yield
    obs.reset()
    obs.disable()
    flight.clear()
    capture_mod._reset_for_tests()
    ts_mod._reset_for_tests()


# -- steering registry edge cases -------------------------------------------


def test_register_steerer_rejects_bad_args():
    with pytest.raises(ValueError):
        steering.register_steerer("", lambda r: r)
    with pytest.raises(ValueError):
        steering.register_steerer("x", "not-callable")


def test_reregister_replaces_idempotently():
    try:
        steering.register_steerer("t_dup", lambda r, **c: "v1")
        assert steering.steer("t_dup", None) == "v1"
        n = steering.steerers().count("t_dup")
        assert n == 1
        steering.register_steerer("t_dup", lambda r, **c: "v2")
        assert steering.steerers().count("t_dup") == 1
        assert steering.steer("t_dup", None) == "v2"
    finally:
        steering._STEERERS.pop("t_dup", None)


def test_unknown_steerer_is_typed_keyerror():
    with pytest.raises(KeyError) as ei:
        steering.steer("no_such_steerer_xyz", None)
    assert "no_such_steerer_xyz" in str(ei.value)
    # and it lists what IS registered, so the typo is debuggable
    assert "have:" in str(ei.value)


def test_steer_counts_dispatches():
    try:
        steering.register_steerer("t_count", lambda r, **c: None)
        steering.steer("t_count", None)
        steering.steer("t_count", None)
        assert obs.counter_value("steering.plans",
                                 steerer="t_count") == 2
    finally:
        steering._STEERERS.pop("t_count", None)


def test_coerce_report_stale_and_garbage():
    assert steering.coerce_report(None) is None
    assert steering.coerce_report("nope") is None
    assert steering.coerce_report({}) is None
    # field-incomplete (a stale pre-ISSUE-7 report shape)
    assert steering.coerce_report({"per_bucket": []}) is None
    good = {"per_bucket": [], "backward_segments": []}
    assert steering.coerce_report(good) == good
    # bench-record wrapping unwraps
    assert steering.coerce_report({"profile": good}) == good


def test_load_report_never_raises(tmp_path):
    assert steering.load_report(str(tmp_path / "missing.json")) is None
    bad = tmp_path / "garbage.json"
    bad.write_text("{not json")
    assert steering.load_report(str(bad)) is None


def test_plan_digest_stable_and_shape_agnostic():
    assert steering.plan_digest((1, 2, 4)) == \
        steering.plan_digest([1, 2, 4])
    assert steering.plan_digest({"a": 1, "b": 2}) == \
        steering.plan_digest({"b": 2, "a": 1})
    assert steering.plan_digest((1, 2)) != steering.plan_digest((1, 3))

    class WithDigest:
        digest = "feedbeef"
    assert steering.plan_digest(WithDigest()) == "feedbeef"


# -- comparator (extracted bench_diff core) ---------------------------------


def _rec(**metrics):
    return {"extras": {"wl": dict(metrics)}}


def test_compare_verdicts():
    base = _rec(tokens_per_sec=100.0)
    assert comp_mod.compare(base, _rec(tokens_per_sec=99.0)).ok
    c = comp_mod.compare(base, _rec(tokens_per_sec=80.0))
    assert not c.ok and c.verdict == "regression"
    assert c.regressed_metrics == ["tokens_per_sec"]
    # nothing in common: explicitly NOT ok (a blind promote is worse
    # than a spurious rollback)
    c = comp_mod.compare({}, {})
    assert c.verdict == "no_overlap" and not c.ok and c.compared == 0


def test_compare_noise_floor_suppresses_tiny_abs_delta():
    # +150% relative on a 0.5ms base stays under the 2ms step_ms floor
    c = comp_mod.compare(_rec(step_ms=0.5), _rec(step_ms=1.25))
    assert c.ok


def test_compare_improvement_direction_aware():
    c = comp_mod.compare(_rec(tokens_per_sec=100.0, step_ms=10.0),
                         _rec(tokens_per_sec=150.0, step_ms=5.0))
    assert c.improvement("tokens_per_sec") == pytest.approx(0.5)
    assert c.improvement("step_ms") == pytest.approx(0.5)
    assert c.improvement("never_measured") is None


def test_compare_to_dict_json_safe_with_zero_base():
    c = comp_mod.compare(_rec(tokens_per_sec=0.0),
                         _rec(tokens_per_sec=5.0))
    doc = json.loads(json.dumps(c.to_dict()))
    rels = [r["rel"] for r in doc["rows"]]
    assert "inf" in rels


def test_compare_counter_growth_flags():
    base = {"counters_total": {"executor.compile_fallbacks": 0},
            "extras": {"wl": {"tokens_per_sec": 100.0}}}
    head = {"counters_total": {"executor.compile_fallbacks": 3},
            "extras": {"wl": {"tokens_per_sec": 100.0}}}
    c = comp_mod.compare(base, head)
    assert not c.ok


# -- daemon hysteresis ------------------------------------------------------


def _daemon(tmp_path, **kw):
    kw.setdefault("merge", False)
    kw.setdefault("hysteresis", 2)
    kw.setdefault("cooldown", 2)
    rule = sd_mod.WatchRule(
        "waste", sd_mod.counter_ratio("serving.padding_waste",
                                      "serving.batches", min_den=8),
        direction=-1, threshold=0.25, floor=0.10,
        steerer="t_steer")
    kw.setdefault("rules", [rule])
    return sd_mod.SteeringDaemon(str(tmp_path), **kw)


def _metrics(tmp_path, ratio, batches=100):
    doc = {"counters_total": {"serving.batches": batches,
                              "serving.padding_waste": ratio * batches}}
    (tmp_path / "metrics.json").write_text(json.dumps(doc))


def test_daemon_oscillation_never_triggers(tmp_path):
    try:
        steering.register_steerer("t_steer", lambda r, **c: [1, 2])
        d = _daemon(tmp_path)
        # baseline 0.2, then alternate clean/breach forever: the clean
        # poll resets the consecutive count each time — no proposal
        for ratio in [0.2] + [0.6, 0.2] * 6:
            _metrics(tmp_path, ratio)
            assert d.poll_once() == []
    finally:
        steering._STEERERS.pop("t_steer", None)


def test_daemon_sustained_breach_proposes_once_then_cooldown(tmp_path):
    try:
        steering.register_steerer("t_steer", lambda r, **c: [1, 2])
        d = _daemon(tmp_path)
        _metrics(tmp_path, 0.2)
        assert d.poll_once() == []          # baseline
        _metrics(tmp_path, 0.6)
        assert d.poll_once() == []          # breach 1 of 2
        props = []
        for _ in range(6):                  # breach persists
            props += d.poll_once()
        # exactly one proposal: breach 2 fires, then the cooldown +
        # rebaseline absorb the persisting level — no storm
        assert len(props) == 1
        assert props[0]["steerer"] == "t_steer"
        assert props[0]["plan_digest"] == steering.plan_digest([1, 2])
        assert (tmp_path / "proposed-t_steer.json").exists()
    finally:
        steering._STEERERS.pop("t_steer", None)


def test_daemon_missing_metric_and_doc_skip(tmp_path):
    try:
        steering.register_steerer("t_steer", lambda r, **c: [1])
        d = _daemon(tmp_path)
        assert d.poll_once() == []          # no metrics.json at all
        # denominator below min_den: extractor yields None, no state
        _metrics(tmp_path, 0.9, batches=2)
        assert d.poll_once() == []
        assert d._state["waste"]["baseline"] is None
    finally:
        steering._STEERERS.pop("t_steer", None)


def test_daemon_broken_steerer_is_flight_recorded(tmp_path):
    def _boom(report, **ctx):
        raise RuntimeError("planner exploded")
    try:
        steering.register_steerer("t_steer", _boom)
        d = _daemon(tmp_path)
        _metrics(tmp_path, 0.2)
        d.poll_once()
        for _ in range(3):
            _metrics(tmp_path, 0.6)
            assert d.poll_once() == []      # proposal attempt fails
        assert obs.counter_value("steering.propose_errors",
                                 steerer="t_steer") >= 1
        kinds = [k for _, k, _ in flight.events()]
        assert "steering.propose_error" in kinds
    finally:
        steering._STEERERS.pop("t_steer", None)


def test_watchrule_validates():
    with pytest.raises(ValueError):
        sd_mod.WatchRule("x", lambda d: 0, direction=2, threshold=0.1,
                         steerer="s")
    with pytest.raises(ValueError):
        sd_mod.WatchRule("x", lambda d: 0, direction=1, threshold=0.0,
                         steerer="s")


def test_default_rules_cover_the_issue_drifts():
    names = {r.name: r.steerer for r in sd_mod.default_rules()}
    assert names == {"serving_padding_waste": "serving_ladder",
                     "lazy_recompile_frac": "lazy_policy",
                     "placement_agreement": "placement"}


# -- canary + audit closure -------------------------------------------------


def _measure(waste):
    return {"extras": {"serving": {
        "serving_padding_waste_frac": waste,
        "rows_per_s": 1000.0 * (1.0 - waste)}}}


def test_canary_promote_and_rollback_audited(tmp_path):
    audit = canary_mod.AuditTrail(str(tmp_path))
    store = canary_mod.PlanStore(str(tmp_path), "t_steer")
    incumbent = _measure(0.5)

    bad = canary_mod.run_canary(
        {"plan": [16], "steerer": "t_steer"}, incumbent,
        lambda plan: _measure(0.9), plan_store=store, audit=audit)
    assert bad.decision == "rolled_back" and store.installs == 0

    good = canary_mod.run_canary(
        {"plan": [2, 4, 16], "steerer": "t_steer"}, incumbent,
        lambda plan: _measure(0.1), plan_store=store, audit=audit,
        require_improvement="serving_padding_waste_frac")
    assert good.decision == "promoted" and store.installs == 1

    entries = audit.entries()
    assert [e["decision"] for e in entries] == ["rolled_back",
                                                "promoted"]
    assert [e["seq"] for e in entries] == [0, 1]
    assert store.active_digest() == good.plan_digest
    assert store.read()["audit_seq"] == 1
    # the flight instants carry the same digests the trail recorded
    fl = {k: f for _, k, f in flight.events()
          if k.startswith("canary.")}
    assert fl["canary.rolled_back"]["plan_digest"] == bad.plan_digest
    assert fl["canary.promoted"]["plan_digest"] == good.plan_digest


def test_canary_no_improvement_demotes(tmp_path):
    audit = canary_mod.AuditTrail(str(tmp_path))
    dec = canary_mod.run_canary(
        {"plan": [8], "steerer": "t"}, _measure(0.5),
        lambda plan: _measure(0.49), audit=audit,
        require_improvement="serving_padding_waste_frac",
        min_improvement=0.05)
    assert not dec.promoted
    assert dec.reason == "no_improvement:serving_padding_waste_frac"


def test_canary_no_overlap_rolls_back(tmp_path):
    dec = canary_mod.run_canary({"plan": [8]}, {}, lambda plan: {})
    assert not dec.promoted and dec.reason == "no_overlap"


def test_plan_store_structurally_refuses_unaudited(tmp_path):
    store = canary_mod.PlanStore(str(tmp_path), "t")
    with pytest.raises(ValueError):
        store.install([1, 2], {"decision": "rolled_back"})
    with pytest.raises(ValueError):   # digest mismatch with the trail
        store.install([1, 2], {"decision": "promoted",
                               "plan_digest": "wrong"})
    with pytest.raises(ValueError):   # PlanStore without AuditTrail
        canary_mod.run_canary({"plan": [8]}, _measure(0.5),
                              lambda plan: _measure(0.1),
                              plan_store=store, audit=None)
    assert store.installs == 0 and store.read() is None


def test_audit_trail_survives_garbage_file(tmp_path):
    p = tmp_path / "steering_audit.json"
    p.write_text("{torn write")
    audit = canary_mod.AuditTrail(str(tmp_path))
    assert audit.entries() == []
    e = audit.append({"decision": "promoted", "plan_digest": "d"})
    assert e["seq"] == 0
    assert audit.entries()[0]["decision"] == "promoted"


# -- sampled capture knob ---------------------------------------------------


def test_sample_every_parse(monkeypatch):
    for raw, want in [("", 0), ("0", 0), ("-3", 0), ("nope", 0),
                      ("7", 7)]:
        capture_mod._reset_for_tests()
        if raw:
            monkeypatch.setenv("PADDLE_TPU_SAMPLE_EVERY", raw)
        else:
            monkeypatch.delenv("PADDLE_TPU_SAMPLE_EVERY",
                               raising=False)
        assert capture_mod.sample_every() == want
    capture_mod._reset_for_tests()


def test_disabled_hook_returns_none_without_counting():
    assert capture_mod.maybe_sample_step("t", object(), object(),
                                         {}) is None
    assert capture_mod._counts == {}


def test_sampling_cadence_and_rolling_report(tmp_path, monkeypatch):
    from paddle_tpu.observability import profiler as prof

    calls = []

    def fake_profile_step(program, scope, feed, **kw):
        calls.append(kw)
        return {"step_ms": 5.0, "overlap_frac": 0.5,
                "per_bucket": [], "backward_segments": []}

    monkeypatch.setattr(prof, "profile_step", fake_profile_step)
    monkeypatch.setattr(prof, "_emit_profile", lambda rep: None)
    monkeypatch.setenv("PADDLE_TPU_SAMPLE_EVERY", "3")
    monkeypatch.setenv("PADDLE_TPU_METRICS_DIR", str(tmp_path))
    capture_mod._reset_for_tests()

    reports = [capture_mod.maybe_sample_step("eng", object(),
                                             object(), {})
               for _ in range(7)]
    fired = [r is not None for r in reports]
    assert fired == [False, False, True, False, False, True, False]
    assert len(calls) == 2 and calls[0]["repeats"] == 1
    assert obs.counter_value("capture.samples", engine="eng") == 2

    files = list(tmp_path.glob("*.profile.json"))
    assert len(files) == 1
    doc = json.loads(files[0].read_text())
    assert doc["schema"] == capture_mod.SAMPLED_PROFILE_SCHEMA
    assert doc["samples"] == 2 and len(doc["history"]) == 2
    assert doc["profile"]["step_ms"] == 5.0


def test_capture_failure_never_breaks_the_step(tmp_path, monkeypatch):
    from paddle_tpu.observability import profiler as prof

    def boom(*a, **kw):
        raise RuntimeError("profiler exploded")

    monkeypatch.setattr(prof, "profile_step", boom)
    monkeypatch.setenv("PADDLE_TPU_SAMPLE_EVERY", "1")
    capture_mod._reset_for_tests()
    assert capture_mod.maybe_sample_step("eng", object(), object(),
                                         {}) is None
    assert obs.counter_value("capture.errors", engine="eng") == 1
    kinds = [k for _, k, _ in flight.events()]
    assert "capture.error" in kinds


def test_history_bounded(tmp_path, monkeypatch):
    from paddle_tpu.observability import profiler as prof

    monkeypatch.setattr(prof, "profile_step",
                        lambda *a, **k: {"step_ms": 1.0})
    monkeypatch.setattr(prof, "_emit_profile", lambda rep: None)
    monkeypatch.setenv("PADDLE_TPU_SAMPLE_EVERY", "1")
    monkeypatch.setenv("PADDLE_TPU_METRICS_DIR", str(tmp_path))
    capture_mod._reset_for_tests()
    for _ in range(capture_mod.HISTORY_CAP + 9):
        capture_mod.maybe_sample_step("eng", object(), object(), {})
    doc = json.loads(next(tmp_path.glob("*.profile.json")).read_text())
    assert len(doc["history"]) == capture_mod.HISTORY_CAP


# -- merge surfacing of sampled reports -------------------------------------


def _write_profile(tmp_path, proc, step_ms):
    doc = {"schema": capture_mod.SAMPLED_PROFILE_SCHEMA,
           "proc": proc, "wrote_at": 1.0,
           "profile": {"step_ms": step_ms, "overlap_frac": 0.5,
                       "phase_ms": {"forward": step_ms / 2}}}
    (tmp_path / ("%s.profile.json" % proc)).write_text(
        json.dumps(doc))


def test_load_sampled_profiles_and_drift(tmp_path):
    _write_profile(tmp_path, "trainer-0", 10.0)
    _write_profile(tmp_path, "trainer-1", 12.0)
    (tmp_path / "trainer-2.profile.json").write_text("{torn")
    sampled = odist.load_sampled_profiles(str(tmp_path))
    assert set(sampled) == {"trainer-0", "trainer-1"}
    drift = odist.sampled_profile_drift(sampled)
    row = drift["step_ms"]
    assert row["min"] == 10.0 and row["max"] == 12.0
    assert row["spread"] == pytest.approx(2.0)
    assert drift["phase_ms.forward"]["max"] == 6.0


def test_merge_job_dir_surfaces_sampled(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_METRICS_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_ROLE", "trainer")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    odist.dump_process()
    _write_profile(tmp_path, "trainer-0", 10.0)
    odist.merge_job_dir(str(tmp_path))
    mdoc = json.loads((tmp_path / "metrics.json").read_text())
    assert "trainer-0" in mdoc["sampled_profiles"]
    assert "step_ms" in mdoc["sampled_profile_drift"]
    # and the per-process section carries its own report
    assert mdoc["processes"]["trainer-0"]["sampled_profile"][
        "profile"]["step_ms"] == 10.0


# -- consumer steerers: serving ladder + lazy policy ------------------------


def test_plan_ladder_quantile_rungs():
    from paddle_tpu.serving import batcher

    rows = [3] * 60 + [13] * 40
    ladder = batcher.plan_ladder(16, rows)
    assert ladder[-1] == 16 and 3 in ladder and 13 in ladder
    assert list(ladder) == sorted(set(ladder))
    # no observations: power-of-two fallback
    assert batcher.plan_ladder(16, []) == batcher.default_ladder(16)
    with pytest.raises(ValueError):
        batcher.plan_ladder(0, rows)


def test_serving_ladder_steerer_registered_and_needs_context():
    from paddle_tpu.serving import batcher  # noqa: F401 — registers

    assert "serving_ladder" in steering.steerers()
    with pytest.raises(ValueError):
        steering.steer("serving_ladder", None)
    plan = steering.steer("serving_ladder", None, max_batch_size=8,
                          batch_rows=[2, 2, 5])
    assert plan[-1] == 8


def test_lazy_policy_plan_and_apply():
    from paddle_tpu.dygraph import lazy

    # thrash: most flushes re-trace and recompiles exceed the cap
    plan = lazy.plan_lazy_policy(recompiles=100, cache_hits=10,
                                 cache_cap=64)
    assert plan["jit_cache_cap"] == 128 and plan["prev_cap"] == 64
    # healthy cache: no change
    plan = lazy.plan_lazy_policy(recompiles=5, cache_hits=100,
                                 cache_cap=64)
    assert plan["jit_cache_cap"] == 64
    # growth is bounded
    plan = lazy.plan_lazy_policy(recompiles=10000, cache_hits=0,
                                 cache_cap=lazy.JIT_CACHE_CAP_MAX)
    assert plan["jit_cache_cap"] == lazy.JIT_CACHE_CAP_MAX

    class FakeEngine:
        JIT_CACHE_CAP = 64
    got = lazy.apply_lazy_policy({"jit_cache_cap": 128},
                                 engine_cls=FakeEngine)
    assert got == 128 and FakeEngine.JIT_CACHE_CAP == 128
    with pytest.raises(ValueError):
        lazy.apply_lazy_policy({"jit_cache_cap": 0},
                               engine_cls=FakeEngine)
    assert "lazy_policy" in steering.steerers()


# -- per-shard PS apply timing ----------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_ps_apply_ms_labeled_by_shard():
    from paddle_tpu.distributed.ps_rpc import PSClient, PSServer

    class MiniScope(dict):
        def local_var_names(self):
            return list(self)

    class MiniExec:
        def _read_var(self, scope, name):
            return scope.get(name)

        def _write_var(self, scope, name, val):
            scope[name] = np.asarray(val)

        def run_block(self, block, scope):
            block(scope)

    scope = MiniScope()
    scope["w"] = np.zeros(4, np.float32)
    before = obs.histogram("ps.apply_ms", shard="0",
                           table="_round").count
    server = PSServer(
        "127.0.0.1:%d" % _free_port(), MiniExec(), scope,
        {"w@GRAD": lambda sc: sc.__setitem__(
            "w", sc["w"] - 0.1 * sc["w@GRAD"])}, fanin=1)
    server.start_background()
    c = PSClient(server._own_endpoint, trainer_id=0)
    try:
        c.send_grad("w@GRAD", np.ones(4, np.float32))
        c.send_barrier()
        c.get_param("w")
        c.fetch_barrier()
    finally:
        c.close()
        server.stop()
    assert obs.histogram("ps.apply_ms", shard="0",
                         table="_round").count == before + 1
    # the dense block apply also lands a per-TABLE series — the hot
    # table name the steerer keys on, not just the hot group
    assert obs.histogram("ps.apply_ms", shard="0",
                         table="w").count >= 1


# -- PS hot-shard steerer (ISSUE 18) ----------------------------------------


from paddle_tpu.observability import ps_steering  # noqa: E402


def _hist(mean, n=8):
    return {"count": n, "sum": mean * n, "min": mean, "max": mean,
            "mean": mean, "p50": mean, "p90": mean, "p99": mean}


def _ps_doc(hot_ms=40.0, cold_ms=10.0, height=16):
    """A merged metrics.json shaped like a 2-shard PS where shard 1
    runs hot on table 'emb'. The server buckets heat over its OWN
    slice, so shard 1's buckets 6-7 (of its span [8, 16), one row per
    bucket) are global rows [14, 16) — the hot tail the plan should
    move."""
    heat = {}
    for b in range(8):
        heat["ps.row_heat{bucket=%d,shard=1,table=emb}" % b] = \
            50 if b >= 6 else 1
        heat["ps.row_heat{bucket=%d,shard=0,table=emb}" % b] = 2
    return {
        "processes": {
            "pserver-0": {"metrics": {"histograms": {
                "ps.apply_ms{shard=0,table=_round}": _hist(cold_ms),
                "ps.apply_ms{shard=0,table=emb}": _hist(cold_ms),
            }, "gauges": {
                "ps.table_rows{shard=0,table=emb}": height,
            }}},
            "pserver-2": {"metrics": {"histograms": {
                "ps.apply_ms{shard=1,table=_round}": _hist(hot_ms),
                "ps.apply_ms{shard=1,table=emb}": _hist(hot_ms),
            }, "gauges": {
                "ps.table_rows{shard=1,table=emb}": height,
            }}},
        },
        "counters_total": heat,
    }


def test_ps_apply_skew_extractor():
    v = ps_steering.apply_skew_value()
    assert v(_ps_doc(hot_ms=40.0, cold_ms=10.0)) == pytest.approx(4.0)
    assert v(_ps_doc(hot_ms=10.0, cold_ms=10.0)) == pytest.approx(1.0)
    # one shard only: no skew is computable
    doc = _ps_doc()
    del doc["processes"]["pserver-2"]
    assert v(doc) is None
    # below the count floor: noise, not signal
    assert ps_steering.apply_skew_value(min_count=64)(_ps_doc()) is None
    assert v({}) is None


def test_ps_migrate_range_steerer_plan():
    assert ps_steering.STEERER_NAME in steering.steerers()
    plan = steering.steer(ps_steering.STEERER_NAME, None,
                          doc=_ps_doc(), height=16, nshards=2)
    assert plan["kind"] == "migrate_range"
    assert plan["table"] == "emb"
    assert plan["from_shard"] == 1 and plan["to_shard"] == 0
    # the hot side of shard 1's span [8, 16): heat sits in the span's
    # buckets 6-7 = global [14, 16), so that tail moves
    assert (plan["lo"], plan["hi"]) == (14, 16)
    assert plan["skew"] == pytest.approx(4.0)
    # plan digests are stable (the audit-chain identity)
    assert steering.plan_digest(plan) == steering.plan_digest(
        steering.steer(ps_steering.STEERER_NAME, None,
                       doc=_ps_doc(), height=16, nshards=2))


def test_ps_steerer_refuses_without_telemetry():
    with pytest.raises(ValueError):
        ps_steering.propose_migrate_range(doc={})
    with pytest.raises(ValueError):
        ps_steering.propose_migrate_range(doc=None, metrics_dir="")
    # skewless telemetry is a refusal, not a no-op plan
    with pytest.raises(ValueError):
        ps_steering.propose_migrate_range(
            doc=_ps_doc(hot_ms=10.0, cold_ms=10.0))


def test_ps_steering_daemon_proposes_migrate_range(tmp_path):
    rule = ps_steering.hot_shard_rule(threshold=0.5, floor=0.25)
    d = sd_mod.SteeringDaemon(
        str(tmp_path), rules=[rule], hysteresis=2, cooldown=2,
        merge=False,
        context={ps_steering.STEERER_NAME: {
            "metrics_dir": str(tmp_path), "height": 16, "nshards": 2}})
    (tmp_path / "metrics.json").write_text(
        json.dumps(_ps_doc(hot_ms=12.0, cold_ms=10.0)))
    assert d.poll_once() == []          # baseline (skew 1.2)
    (tmp_path / "metrics.json").write_text(
        json.dumps(_ps_doc(hot_ms=40.0, cold_ms=10.0)))
    assert d.poll_once() == []          # breach 1 of 2
    props = d.poll_once()               # breach 2: propose
    assert len(props) == 1
    art = props[0]
    assert art["steerer"] == ps_steering.STEERER_NAME
    assert art["plan"]["kind"] == "migrate_range"
    assert art["plan"]["table"] == "emb"
    path = tmp_path / ("proposed-%s.json" % ps_steering.STEERER_NAME)
    assert path.exists()
    on_disk = json.loads(path.read_text())
    assert on_disk["plan_digest"] == art["plan_digest"]
    kinds = [k for _, k, _ in flight.events()]
    assert "steering.proposed" in kinds


def test_ps_migrate_range_canary_applies_through_protocol(tmp_path):
    """The canary wiring the drill uses: apply_fn IS the live
    migration call; promotion installs through the PlanStore, and an
    injected regression rolls back without installing."""
    plan = steering.steer(ps_steering.STEERER_NAME, None,
                          doc=_ps_doc(), height=16, nshards=2)
    proposal = {"plan": plan,
                "plan_digest": steering.plan_digest(plan),
                "steerer": ps_steering.STEERER_NAME}
    applied = []
    store = canary_mod.PlanStore(str(tmp_path),
                                 ps_steering.STEERER_NAME)
    audit = canary_mod.AuditTrail(str(tmp_path))
    incumbent = {"configs": {"ps_rebalance": {"rounds_per_s": 50.0}}}

    dec = canary_mod.run_canary(
        proposal, incumbent,
        measure=lambda p: {"configs": {
            "ps_rebalance": {"rounds_per_s": 60.0}}},
        apply_fn=lambda p: applied.append(
            (p["table"], p["lo"], p["hi"], p["to_shard"])),
        plan_store=store, audit=audit)
    assert dec.promoted and applied == [("emb", 14, 16, 0)]
    assert store.active_digest() == proposal["plan_digest"]
    assert audit.entries()[-1]["decision"] == "promoted"
    assert audit.entries()[-1]["plan_digest"] == dec.plan_digest

    rolled = []
    dec2 = canary_mod.run_canary(
        proposal, incumbent,
        measure=lambda p: {"configs": {
            "ps_rebalance": {"rounds_per_s": 20.0}}},
        apply_fn=lambda p: applied.append("again"),
        rollback_fn=lambda p: rolled.append(p["table"]),
        plan_store=store, audit=audit)
    assert not dec2.promoted and rolled == ["emb"]
    assert audit.entries()[-1]["decision"] == "rolled_back"
    # the rollback never touched the active-plan pointer
    assert store.active_digest() == proposal["plan_digest"]


def test_ps_row_load_extractor():
    doc = _ps_doc()
    # shard 1: 6*1 + 2*50 = 106 touches; shard 0: 8*2 = 16
    load = ps_steering.shard_row_load(doc)
    assert load == {0: 16.0, 1: 106.0}
    v = ps_steering.row_load_skew_value()
    assert v(doc) == pytest.approx(106.0 / 16.0)
    # counters, not timings: the same doc always yields the same skew
    assert v(doc) == v(json.loads(json.dumps(doc)))
    # one shard's census alone is no signal
    solo = _ps_doc()
    solo["counters_total"] = {
        k: n for k, n in solo["counters_total"].items()
        if "shard=1" in k}
    assert v(solo) is None
    # below the per-shard touch floor: noise
    assert ps_steering.row_load_skew_value(min_rows=32)(doc) is None
    assert v({}) is None


def test_ps_migrate_range_by_row_heat():
    """The drill's deterministic path: hot shard from row counters,
    same span-local split as the wall-time path."""
    plan = steering.steer(ps_steering.STEERER_NAME, None,
                          doc=_ps_doc(), height=16, nshards=2,
                          by="row_heat")
    assert plan["by"] == "row_heat"
    assert plan["table"] == "emb"
    assert plan["from_shard"] == 1 and plan["to_shard"] == 0
    assert (plan["lo"], plan["hi"]) == (14, 16)
    assert plan["skew"] == pytest.approx(round(106.0 / 16.0, 4))
    with pytest.raises(ValueError):
        ps_steering.propose_migrate_range(doc=_ps_doc(), height=16,
                                          nshards=2, by="bogus")


def test_ps_row_load_rule_wiring():
    rule = ps_steering.row_load_rule(threshold=0.5, floor=0.25)
    assert rule.name == "ps_row_load_skew"
    assert rule.steerer == ps_steering.STEERER_NAME
    assert rule.direction == -1
    assert rule.value_fn(_ps_doc()) == pytest.approx(106.0 / 16.0)


# -- ISSUE 20: weighted multi-metric objectives -----------------------------


def test_objective_validates():
    with pytest.raises(ValueError):
        comp_mod.Objective({})
    with pytest.raises(ValueError):
        comp_mod.Objective({"step_ms": 0.0})
    # contradicting a WATCHED direction is a configuration bug
    with pytest.raises(ValueError, match="conflict"):
        comp_mod.Objective({"step_ms": 1.0},
                           directions={"step_ms": +1})
    # an unwatched metric needs an explicit direction
    with pytest.raises(ValueError, match="direction"):
        comp_mod.Objective({"my_custom": 1.0})
    with pytest.raises(ValueError):
        comp_mod.Objective({"my_custom": 1.0},
                           directions={"my_custom": 2})
    ob = comp_mod.Objective({"my_custom": 1.0},
                            directions={"my_custom": +1})
    assert ob.directions == {"my_custom": +1}


def test_objective_weights_are_relative():
    base = _rec(tokens_per_sec=100.0, step_ms=10.0)
    head = _rec(tokens_per_sec=150.0, step_ms=20.0)
    s2 = comp_mod.compare(base, head, objective=comp_mod.Objective(
        {"tokens_per_sec": 2.0, "step_ms": 2.0})).objective_score
    s1 = comp_mod.compare(base, head, objective=comp_mod.Objective(
        {"tokens_per_sec": 1.0, "step_ms": 1.0})).objective_score
    assert s2 == pytest.approx(s1)
    # +50% tokens (gain +0.5) vs +100% step_ms (gain -1.0), equal
    # weight: net -0.25
    assert s1 == pytest.approx(-0.25)


def test_objective_missing_metric_keeps_its_weight():
    ob = comp_mod.Objective({"tokens_per_sec": 1.0, "mfu_est": 1.0})
    c = comp_mod.compare(_rec(tokens_per_sec=100.0),
                         _rec(tokens_per_sec=150.0), objective=ob)
    res = c.objective_result()
    missing = [t for t in res["terms"] if t.get("missing")]
    assert [t["metric"] for t in missing] == ["mfu_est"]
    assert missing[0]["contribution"] == 0.0
    # the absent term still dilutes: half the single-metric score
    solo = comp_mod.compare(
        _rec(tokens_per_sec=100.0), _rec(tokens_per_sec=150.0),
        objective=comp_mod.Objective({"tokens_per_sec": 1.0}))
    assert res["score"] == pytest.approx(solo.objective_score / 2.0)


def test_objective_noise_floor_zeroes_the_term():
    # +1ms on step_ms sits under its 2ms ABS_NOISE_FLOOR default
    c = comp_mod.compare(
        _rec(step_ms=10.0), _rec(step_ms=11.0),
        objective=comp_mod.Objective({"step_ms": 1.0}))
    res = c.objective_result()
    (term,) = res["terms"]
    assert term["floored"] and term["contribution"] == 0.0
    assert res["score"] == 0.0
    # zero net gain is NOT an improvement
    assert not c.ok and c.verdict == "objective_regression"


def test_objective_promotes_bounded_regression_flat_rejects():
    """The whole point: a net win with ONE bounded regression. The
    flat comparator vetoes on the waste row; the weighted objective
    trades it against the larger rows_per_s win."""
    base = _rec(rows_per_s=1000.0, serving_padding_waste_frac=0.10)
    head = _rec(rows_per_s=1500.0, serving_padding_waste_frac=0.30)
    flat = comp_mod.compare(base, head)
    assert not flat.ok and flat.verdict == "regression"
    ob = comp_mod.Objective({"rows_per_s": 5.0,
                             "serving_padding_waste_frac": 1.0})
    c = comp_mod.compare(base, head, objective=ob)
    assert c.ok and c.verdict == "objective_improved"
    # (5/6)*0.5 - (1/6)*2.0
    assert c.objective_score == pytest.approx(5.0 / 12 - 1.0 / 3)
    doc = c.to_dict()["objective"]
    assert doc["config"] == ob.to_dict()
    assert doc["result"]["ok"]


def test_objective_hard_floor_vetoes_unconditionally():
    ob = comp_mod.Objective({"rows_per_s": 1.0},
                            hard_floors={"p50_ms": 15.0})
    c = comp_mod.compare(_rec(rows_per_s=1000.0, p50_ms=10.0),
                         _rec(rows_per_s=2000.0, p50_ms=16.0),
                         objective=ob)
    assert not c.ok and c.verdict == "hard_floor"
    (v,) = c.objective_result()["hard_floor_violations"]
    assert v["metric"] == "p50_ms" and v["head"] == 16.0 \
        and v["bound"] == 15.0
    # comfortably inside the SLO: the same objective promotes
    ok = comp_mod.compare(_rec(rows_per_s=1000.0, p50_ms=10.0),
                          _rec(rows_per_s=2000.0, p50_ms=10.0),
                          objective=ob)
    assert ok.ok and ok.verdict == "objective_improved"


def test_objective_counter_regression_still_vetoes():
    base = {"counters_total": {"executor.compile_fallbacks": 0},
            "extras": {"wl": {"rows_per_s": 1000.0}}}
    head = {"counters_total": {"executor.compile_fallbacks": 3},
            "extras": {"wl": {"rows_per_s": 2000.0}}}
    c = comp_mod.compare(base, head, objective=comp_mod.Objective(
        {"rows_per_s": 1.0}))
    assert not c.ok and c.verdict == "counter_regression"


def test_default_compare_dict_bit_compatible():
    # no objective -> the PR 16-19 audit/gate schema, byte for byte
    c = comp_mod.compare(_rec(tokens_per_sec=100.0),
                         _rec(tokens_per_sec=100.0))
    assert "objective" not in c.to_dict()
    assert c.objective_score is None


def test_objective_round_trips():
    ob = comp_mod.Objective(
        {"rows_per_s": 2.0, "my_custom": 1.0},
        directions={"my_custom": -1},
        floors={"rows_per_s": 10.0},
        hard_floors={"p99_ms": 250.0})
    assert comp_mod.Objective.from_dict(ob.to_dict()).to_dict() \
        == ob.to_dict()


# -- ISSUE 20: interleaved A/B canary windows -------------------------------


_AB_OBJECTIVE = {"weights": {"rows_per_s": 1.0,
                             "serving_padding_waste_frac": 1.0},
                 "floors": {"serving_padding_waste_frac": 0.02}}


def _drifting_measure(incumbent_waste, candidate_waste, drift):
    """measure(plan_or_None) whose throughput inflates by ``drift``
    per WINDOW regardless of the plan — the confounder interleaving
    exists to cancel."""
    clock = {"n": 0}

    def measure(plan):
        rec = _measure(incumbent_waste if plan is None
                       else candidate_waste)
        rec["extras"]["serving"]["rows_per_s"] *= \
            (1.0 + drift) ** clock["n"]
        clock["n"] += 1
        return rec
    return measure, clock


def test_ab_canary_rejects_drift_masked_regression(tmp_path):
    """The drill's divergence as a unit test: under monotone load
    drift the flat before/after canary promotes a worse plan; the
    interleaved A/B objective canary rejects the same plan."""
    proposal = {"plan": [5, 16], "steerer": "t_ab",
                "objective": dict(_AB_OBJECTIVE), "ab_pairs": 3}

    # flat protocol vs a stale incumbent record: drift masquerades
    # as plan improvement (+10%/window for 5 idle windows) and the
    # 0.1 waste delta hides under the 0.15 flat noise floor
    measure, clock = _drifting_measure(0.2, 0.3, 0.10)
    stale = measure(None)
    clock["n"] += 5
    flat = canary_mod.run_canary({"plan": [5, 16], "steerer": "t_ab"},
                                 stale, measure)
    assert flat.promoted and flat.reason == "ok"

    # interleaved: adjacent windows see the true -0.1 waste hit and
    # barely-moved rows; every pair votes regression
    measure, _ = _drifting_measure(0.2, 0.3, 0.10)
    audit = canary_mod.AuditTrail(str(tmp_path))
    dec = canary_mod.run_ab_canary(proposal, measure, audit=audit)
    assert not dec.promoted
    assert dec.reason == "ab_majority:0/3"

    entry = audit.entries()[-1]
    assert entry["protocol"] == canary_mod.AB_PROTOCOL
    assert entry["decision"] == "rolled_back"
    assert entry["pairs"] == 3 and entry["ok_pairs"] == 0
    assert len(entry["windows"]) == 6
    assert [w["phase"] for w in entry["windows"]] == \
        ["incumbent", "candidate"] * 3
    assert [w["seq"] for w in entry["windows"]] == list(range(6))
    # the proposal's objective block was adopted and recorded
    assert entry["objective"]["weights"] == _AB_OBJECTIVE["weights"]
    assert entry["objective_score"] < 0
    for pd in entry["pair_verdicts"]:
        assert not pd["ok"]
        assert pd["verdict"] == "objective_regression"
        terms = {t["metric"] for t in
                 pd["comparison"]["objective"]["result"]["terms"]}
        assert terms == {"rows_per_s", "serving_padding_waste_frac"}
    # every window was metered
    assert obs.counter_value("canary.windows", phase="incumbent",
                             steerer="t_ab") == 3
    assert obs.counter_value("canary.windows", phase="candidate",
                             steerer="t_ab") == 3
    assert obs.gauge_value("steering.objective_score",
                           steerer="t_ab") == \
        pytest.approx(entry["objective_score"])


def test_ab_canary_promotes_and_installs(tmp_path):
    measure, _ = _drifting_measure(0.2, 0.05, 0.0)
    audit = canary_mod.AuditTrail(str(tmp_path))
    store = canary_mod.PlanStore(str(tmp_path), "t_ab")
    calls = []
    dec = canary_mod.run_ab_canary(
        {"plan": [2, 4, 16], "steerer": "t_ab",
         "objective": dict(_AB_OBJECTIVE)},
        measure, pairs=2,
        apply_fn=lambda p: calls.append("apply"),
        revert_fn=lambda p: calls.append("revert"),
        promote_fn=lambda p: calls.append("promote"),
        plan_store=store, audit=audit)
    assert dec.promoted and dec.reason == "ab_majority:2/2"
    assert calls == ["revert", "apply"] * 2 + ["promote"]
    assert store.installs == 1
    assert store.active_digest() == dec.plan_digest
    entry = audit.entries()[-1]
    assert entry["decision"] == "promoted"
    assert entry["objective_score"] > 0
    assert len(entry["windows"]) == 4
    fl = {k: f for _, k, f in flight.events()
          if k == "canary.promoted"}
    assert fl["canary.promoted"]["protocol"] == canary_mod.AB_PROTOCOL
    assert fl["canary.promoted"]["ok_pairs"] == 2


def test_ab_canary_hard_floor_overrides_the_vote(tmp_path):
    def measure(plan):
        rec = _measure(0.05 if plan is not None else 0.2)
        rec["extras"]["serving"]["p50_ms"] = \
            16.0 if plan is not None else 10.0
        return rec
    ob = comp_mod.Objective({"rows_per_s": 1.0},
                            hard_floors={"p50_ms": 15.0})
    dec = canary_mod.run_ab_canary({"plan": [8], "steerer": "t_ab"},
                                   measure, pairs=3, objective=ob)
    assert not dec.promoted and dec.reason == "ab_hard_floor"


def test_ab_canary_min_score_demotes_majority(tmp_path):
    measure, _ = _drifting_measure(0.2, 0.05, 0.0)
    dec = canary_mod.run_ab_canary(
        {"plan": [2, 16], "steerer": "t_ab",
         "objective": dict(_AB_OBJECTIVE)},
        measure, pairs=3, min_score=10.0)
    assert not dec.promoted
    assert dec.reason == "ab_no_objective_improvement"


def test_ab_pairs_resolution(monkeypatch):
    assert canary_mod._ab_pairs_default() == canary_mod.DEFAULT_AB_PAIRS
    monkeypatch.setenv(canary_mod.AB_PAIRS_ENV, "5")
    assert canary_mod._ab_pairs_default() == 5
    monkeypatch.setenv(canary_mod.AB_PAIRS_ENV, "bogus")
    assert canary_mod._ab_pairs_default() == canary_mod.DEFAULT_AB_PAIRS
    monkeypatch.setenv(canary_mod.AB_PAIRS_ENV, "-3")
    assert canary_mod._ab_pairs_default() == 1


# -- ISSUE 20: daemon objective wiring + windowed extractors ----------------


def test_watchrule_objective_rides_the_proposal(tmp_path):
    ob = comp_mod.Objective({"rows_per_s": 2.0,
                             "serving_padding_waste_frac": 1.0})
    rule = sd_mod.WatchRule(
        "waste", sd_mod.counter_ratio("serving.padding_waste",
                                      "serving.batches", min_den=8),
        direction=-1, threshold=0.25, floor=0.10,
        steerer="t_steer", objective=ob, ab_pairs=4)
    try:
        steering.register_steerer("t_steer", lambda r, **c: [1, 2])
        d = _daemon(tmp_path, rules=[rule])
        _metrics(tmp_path, 0.2)
        assert d.poll_once() == []
        props = []
        for ratio in [0.6] * 3:
            _metrics(tmp_path, ratio)
            props += d.poll_once()
        assert len(props) == 1
        assert props[0]["ab_pairs"] == 4
        # the artifact carries a JSON objective run_ab_canary adopts
        assert comp_mod.Objective.from_dict(
            props[0]["objective"]).to_dict() == ob.to_dict()
    finally:
        steering._STEERERS.pop("t_steer", None)


def test_windowed_counter_ratio_prefers_last_window():
    v = sd_mod.windowed_counter_ratio("serving.padding_waste",
                                      "serving.batches", min_den=8)
    lifetime = {"counters_total": {"serving.padding_waste": 50.0,
                                   "serving.batches": 100.0}}
    assert v(lifetime) == pytest.approx(0.5)
    windowed = dict(lifetime)
    windowed["series_windows"] = {
        "serving.padding_waste": {"kind": "counter", "delta": 30.0},
        "serving.batches": {"kind": "counter", "delta": 50.0}}
    assert v(windowed) == pytest.approx(0.6)
    # window denominator under min_den: lifetime fallback, not None
    starving = dict(lifetime)
    starving["series_windows"] = {
        "serving.padding_waste": {"kind": "counter", "delta": 1.0},
        "serving.batches": {"kind": "counter", "delta": 2.0}}
    assert v(starving) == pytest.approx(0.5)


def test_default_waste_rule_is_windowed():
    rules = {r.name: r for r in sd_mod.default_rules()}
    v = rules["serving_padding_waste"].value_fn
    doc = {"counters_total": {"serving.padding_waste": 10.0,
                              "serving.batches": 100.0},
           "series_windows": {
               "serving.padding_waste": {"kind": "counter",
                                         "delta": 40.0},
               "serving.batches": {"kind": "counter",
                                   "delta": 50.0}}}
    # lifetime says 0.1; the last window says 0.8 — window wins
    assert v(doc) == pytest.approx(0.8)


# -- ISSUE 20: PS steering over windowed rates ------------------------------


def _ps_windowed_doc():
    return {"series_windows": {
        "ps.row_heat{bucket=0,shard=0,table=emb}":
            {"kind": "counter", "delta": 10.0},
        "ps.row_heat{bucket=3,shard=1,table=emb}":
            {"kind": "counter", "delta": 90.0},
        "ps.apply_ms{shard=0,table=_round}#sum":
            {"kind": "counter", "delta": 100.0},
        "ps.apply_ms{shard=0,table=_round}#count":
            {"kind": "counter", "delta": 10.0},
        "ps.apply_ms{shard=1,table=_round}#sum":
            {"kind": "counter", "delta": 400.0},
        "ps.apply_ms{shard=1,table=_round}#count":
            {"kind": "counter", "delta": 10.0}}}


def test_ps_windowed_row_load_beats_lifetime():
    doc = _ps_windowed_doc()
    assert ps_steering.windowed_shard_row_load(doc) == \
        {0: 10.0, 1: 90.0}
    # lifetime counters say balanced; the last window says 9x skew
    doc["counters_total"] = {
        "ps.row_heat{bucket=0,shard=0,table=emb}": 500.0,
        "ps.row_heat{bucket=3,shard=1,table=emb}": 500.0}
    assert ps_steering.row_load_skew_value()(doc) == pytest.approx(9.0)
    # windowed touches under min_rows: falls back to lifetime (1.0)
    assert ps_steering.row_load_skew_value(min_rows=50)(doc) \
        == pytest.approx(1.0)
    assert ps_steering.windowed_shard_row_load({}) == {}


def test_ps_windowed_apply_means():
    doc = _ps_windowed_doc()
    assert ps_steering.windowed_shard_apply_means(doc) == \
        {0: 10.0, 1: 40.0}
    assert ps_steering.apply_skew_value()(doc) == pytest.approx(4.0)
    # below min_count per window: windowed path yields nothing and
    # there is no lifetime histogram either -> None
    assert ps_steering.apply_skew_value(min_count=20)(doc) is None
