"""Unified runtime observability: metrics registry + span tracing.

Every execution path reports here — static ``Executor`` (compiled and
interpreter), the lazy dygraph engine, the mesh data-parallel engine,
the pipeline engine, the LoD-lowering planner, and the memory facade —
so "why was step N slow", "how often did the lazy engine recompile" and
"did the pipeline bubble grow" are answerable without print-debugging.

Opt-in: set ``PADDLE_TPU_METRICS=1`` (or the ``FLAGS_tpu_metrics``
flag via ``fluid.set_flags``), or call ``observability.enable()``.
When disabled (the default) every instrumentation site is a single
cached-module-attribute load plus a branch — a no-op on the hot path.

Metric families (see README "Runtime observability"):

=====================================  ======================================
``executor.steps{path=...}``           counter: compiled | interpreter steps
``executor.step_ms{path=...}``         histogram: host step latency
``executor.ops{type=...}``             counter: interpreter per-op executions
``executor.compiles``                  counter: whole-program (re)compiles
``executor.jit_traces``                counter: per-shape XLA (re)traces
``executor.compile_fallbacks``         counter: compiled -> interpreter drops
``lod_lowering.declines{op_type=...}`` counter: ragged lowering declines
``lazy.flushes``                       counter: lazy-engine flushes
``lazy.cache_hits`` / ``lazy.recompiles``  counter: flush jit cache hit/miss
``lazy.graph_nodes``                   histogram: nodes per flushed graph
``dygraph.ops{dispatch=...}``          counter: traced eager/lazy ops
``parallel.steps`` / ``.compiles``     counter: mesh-engine steps/compiles
``parallel.collective_bytes``          counter: bytes allreduced per step
``parallel.step_ms``                   histogram: mesh step latency
``pipeline.steps`` / ``.step_ms``      counter / histogram
``pipeline.bubble_fraction``           gauge: (S-1)/(M+S-1) GPipe bubble
``pipeline.boundary_bytes{boundary=}`` gauge: rotating-buffer payload
``memory.*_bytes``                     gauge: live/peak/limit device bytes
``serving.*``                          serving engine + fleet router
                                       (always-on; incl. ``shed{class=}``,
                                       ``hedges``, ``hedge_wasted``,
                                       ``fleet_retries``, ``dedup_hits``,
                                       ``replica_ejections{cause=}``,
                                       ``replica_rejoins`` — see
                                       ``paddle_tpu/serving/metrics.py``)
``rpc.retries{method=}``               counter: PS client retries per rpc
``rpc.timeouts{method=}``              counter: per-attempt deadline trips
``rpc.latency_ms{method=}``            histogram: per-ATTEMPT reply latency
                                       (retries observe separately)
``ps.evictions`` / ``ps.readmissions`` counter: heartbeat-monitor actions
``ps.failovers{cause=}``               counter: client endpoint advances
                                       (cause: transport | redirect)
``ps.promotions``                      counter: backup -> primary
``ps.catchup_ms``                      histogram: rejoin snapshot catch-up
``ps.replication_lag_rounds{backup=}`` gauge: rounds the backup is behind
                                       (0 after each ack; frozen = dropped)
``ps.replication_bytes{mode=}``        counter: shipped payload, full | delta
``ps.delta_rounds`` / ``ps.anchor_rounds``  counter: delta vs full-anchor ships
``ps.lease_renewals``                  counter: primary lease renewal acks
``ps.lease_expiries{shard=}``          counter: backup lease-view expiries
``fault.injected{side=,kind=}``        counter: injected RPC-frame faults
``checkpoint.save_ms``                 histogram: atomic checkpoint commit
``checkpoint.bytes``                   counter: checkpointed payload bytes
``checkpoint.delta_bytes``             counter: incremental-save fresh bytes
``checkpoint.shards_reused``           counter: shards linked from prev ckpt
``checkpoint.corrupt``                 counter: rotations failing sha256
=====================================  ======================================

The ``rpc.* / ps.* / fault.* / checkpoint.*`` families (like
``serving.*``) record unconditionally — recovery events are rare, and
CI asserts on them without needing ``PADDLE_TPU_METRICS``. The
``method=`` label on ``rpc.retries`` / ``rpc.timeouts`` exists for
retry-policy tuning: a rising retry rate under a clean network on ONE
method (say ``send_barrier``) means that call shape's per-attempt
deadline is mis-set, not the transport.

Export: ``dump()`` -> JSON-able dict, ``dump(fmt="prometheus")`` ->
text exposition format, ``chrome_trace()`` / ``write_chrome_trace()``
-> Perfetto-loadable ``trace_event`` JSON merging all host spans
(including the legacy ``fluid.profiler`` timeline).

Distributed (ISSUE 5, ``observability/distributed`` +
``observability/flight``): setting ``PADDLE_TPU_METRICS_DIR`` arms
this layer plus a periodic/at-exit/on-SIGTERM per-process dumper;
rpc headers carry ``trace_id``/``parent_span`` so one sync round or
serving request is one cross-process trace; every recovery decision
lands in a bounded always-on flight-recorder ring; and the launch
supervisor merges everything into a job-level ``metrics.json`` + one
chrome-trace ``trace.json`` (``tools/ft_timeline.py`` prints the
ordered cross-process postmortem). See README "Distributed
observability".
"""
from __future__ import annotations

import os
from typing import Dict, Optional

from . import flight  # noqa: F401
from . import tracing  # noqa: F401
from . import distributed  # noqa: F401
from . import profiler  # noqa: F401
from . import spool  # noqa: F401
from .registry import Counter, Gauge, Histogram, MetricsRegistry  # noqa: F401
from .tracing import span  # noqa: F401

__all__ = ["enable", "disable", "enabled", "metrics", "counter", "gauge",
           "histogram", "inc", "set_gauge", "observe", "counter_value",
           "gauge_value", "span", "dump", "dump_prometheus",
           "chrome_trace", "write_chrome_trace", "reset",
           "MetricsRegistry", "Counter", "Gauge", "Histogram",
           "flight", "distributed", "profiler", "spool"]

_registry = MetricsRegistry()
_enabled = False


def _init_from_env() -> None:
    """Arm from the environment before core.flags is even imported —
    observability must not drag the flag module (and transitively jax)
    in at import time. Precedence matches core/flags._init_from_env
    exactly (FLAGS_tpu_metrics primary, PADDLE_TPU_METRICS alias) so
    the flag value and this layer's armed state can never diverge.

    A set ``PADDLE_TPU_METRICS_DIR`` additionally arms the layer AND
    the per-process dumper (``observability.distributed``): asking for
    a job-level dump dir without metrics would produce empty dumps, so
    the dir is the one switch a distributed job needs."""
    raw = os.environ.get("FLAGS_tpu_metrics")
    if raw is None:
        raw = os.environ.get("PADDLE_TPU_METRICS", "")
    if raw.lower() in ("1", "true", "yes", "on"):
        enable()
    if distributed.metrics_dir() is not None:
        enable()
        distributed.arm_from_env()
    # the crash postmortem hook is unconditional (a black box that
    # needs arming is not a black box): it chains the existing
    # excepthook and, with no metrics dir, only prints the flight-ring
    # tail to stderr before the normal traceback
    flight.install_excepthook()


def enabled() -> bool:
    return _enabled


def _sync_flag(on: bool) -> None:
    """Keep FLAGS_tpu_metrics truthful when enable()/disable() is
    called directly (get_flags must report the armed state). Written
    via sys.modules so this never forces core.flags (and its package
    init) to load early — if flags isn't loaded yet, its own env init
    resolves to the same value."""
    import sys

    fl = sys.modules.get(__package__.rsplit(".", 1)[0] + ".core.flags")
    if fl is not None:
        fl._values["FLAGS_tpu_metrics"] = bool(on)


def enable() -> None:
    global _enabled
    _enabled = True
    tracing._set_metrics_on(True)
    _sync_flag(True)


def disable() -> None:
    global _enabled
    _enabled = False
    tracing._set_metrics_on(False)
    _sync_flag(False)


def metrics() -> MetricsRegistry:
    return _registry


# -- direct metric handles (create regardless of enabled: tests and
# callers that hold a handle pay the branch themselves) --------------------

def counter(name: str, **labels) -> Counter:
    return _registry.counter(name, **labels)


def gauge(name: str, **labels) -> Gauge:
    return _registry.gauge(name, **labels)


def histogram(name: str, **labels) -> Histogram:
    return _registry.histogram(name, **labels)


# -- guarded one-shot helpers (the instrumentation-site surface) -----------

def inc(name: str, n: int = 1, **labels) -> None:
    if _enabled:
        _registry.counter(name, **labels).inc(n)


def set_gauge(name: str, v, **labels) -> None:
    if _enabled:
        _registry.gauge(name, **labels).set(v)


def observe(name: str, v, **labels) -> None:
    if _enabled:
        _registry.histogram(name, **labels).observe(v)


def counter_value(name: str, **labels):
    return _registry.counter_value(name, **labels)


def gauge_value(name: str, **labels):
    return _registry.gauge_value(name, **labels)


# -- export ----------------------------------------------------------------

def _refresh_memory_gauges() -> None:
    """Pull-style gauges: live/peak device bytes are sampled at dump
    time (the backend owns the counters; polling every step would be
    overhead for numbers only a dump reader looks at). memory_usage
    itself writes the ``memory.*_bytes`` gauges when the layer is
    enabled; a disabled dump stays a pure observation and creates
    nothing."""
    if not _enabled:
        return
    try:
        from ..core.memory import memory_usage

        memory_usage()
    except Exception:
        pass


def dump(fmt: str = "json") -> object:
    """Snapshot of every metric. ``fmt="json"`` (default) returns a
    JSON-able dict; ``fmt="prometheus"`` returns the text exposition
    format."""
    _refresh_memory_gauges()
    if fmt == "prometheus":
        return _registry.to_prometheus()
    if fmt != "json":
        raise ValueError("unknown dump format %r" % fmt)
    out = _registry.snapshot()
    out["spans"] = tracing.stats()
    out["enabled"] = _enabled
    return out


def dump_prometheus() -> str:
    return dump(fmt="prometheus")


def _legacy_profiler_events():
    """The old ``fluid.profiler`` timeline — live session if one is
    running, else the last finished session's snapshot — so the chrome
    export keeps the ``get_trace_events()`` contract alive."""
    try:
        if tracing.profiler_session_active():
            return []   # live session spans are already in the buffer
        return profiler.get_trace_events()
    except Exception:
        return []


def chrome_trace() -> Dict:
    """Perfetto-loadable ``trace_event`` JSON merging the span buffer
    with the legacy profiler timeline."""
    return tracing.chrome_trace(extra_events=_legacy_profiler_events())


def write_chrome_trace(path: str) -> str:
    return tracing.write_chrome_trace(
        path, extra_events=_legacy_profiler_events())


def reset() -> None:
    """Clear all metrics and buffered spans — including the legacy
    profiler's finished-session snapshot, so a post-reset
    chrome_trace() is actually empty (enabled state is kept)."""
    _registry.reset()
    tracing.clear()
    try:
        del profiler._last_trace[:]
    except Exception:
        pass


_init_from_env()
