"""Role makers: who am I in the distributed job.

Parity: /root/reference/python/paddle/fluid/incubate/fleet/base/
role_maker.py (:441 PaddleCloudRoleMaker env contract, :126
UserDefinedRoleMaker). The TPU runtime discovers peers through the
coordination service (jax.distributed); these classes answer the same
questions from the same PADDLE_* env vars so launch tooling ports over.
"""
from __future__ import annotations

import os


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_endpoints = []
        self._server_endpoints = []
        self._role_is_generated = False

    def generate_role(self):
        raise NotImplementedError

    def _ensure(self):
        if not self._role_is_generated:
            self.generate_role()

    def is_worker(self):
        self._ensure()
        return self._role == Role.WORKER

    def is_server(self):
        self._ensure()
        return self._role == Role.SERVER

    def is_first_worker(self):
        self._ensure()
        return self._role == Role.WORKER and self._current_id == 0

    def worker_index(self):
        self._ensure()
        return self._current_id

    def server_index(self):
        self._ensure()
        return self._current_id

    def worker_num(self):
        self._ensure()
        return len(self._worker_endpoints) or 1

    def server_num(self):
        self._ensure()
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        self._ensure()
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        self._ensure()
        return self._server_endpoints


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_endpoints = ["127.0.0.1:0"] * worker_num
        self._server_endpoints = list(server_endpoints or [])
        self._worker_num = worker_num

    def worker_num(self):
        return self._worker_num

    def generate_role(self):
        self._role_is_generated = True


class PaddleCloudRoleMaker(RoleMakerBase):
    """Reads the PADDLE_* env contract set by launch tooling
    (reference role_maker.py:441)."""

    def __init__(self, is_collective=False):
        super().__init__()
        self._is_collective = is_collective

    def generate_role(self):
        if self._is_collective:
            self._role = Role.WORKER
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
            eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
            self._worker_endpoints = [e for e in eps.split(",") if e]
        else:
            training_role = os.environ.get("TRAINING_ROLE", "TRAINER")
            if training_role == "TRAINER":
                self._role = Role.WORKER
                self._current_id = int(
                    os.environ.get("PADDLE_TRAINER_ID", "0"))
            else:
                self._role = Role.SERVER
            eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
            self._server_endpoints = [e for e in eps.split(",") if e]
            if self._role == Role.SERVER:
                # reference role_maker.py:477: server id = index of this
                # host's POD_IP:PADDLE_PORT in the endpoint list
                cur = "%s:%s" % (os.environ.get("POD_IP", ""),
                                 os.environ.get("PADDLE_PORT", ""))
                self._current_id = (self._server_endpoints.index(cur)
                                    if cur in self._server_endpoints else 0)
            self._worker_endpoints = ["w:%d" % i for i in range(int(
                os.environ.get("PADDLE_TRAINERS_NUM", "1")))]
        self._role_is_generated = True
