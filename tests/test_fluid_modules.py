"""Top-level fluid module parity: nets, lod_tensor, average, debugger,
communicator, evaluator, input — plus the op tail their paths use
(chunk_eval, positive_negative_pair, sequence_enumerate/erase,
proximal_adagrad, dgc_momentum, dgc_clip_by_norm, ref_by_trainer_id).

Parity: /root/reference/python/paddle/fluid/{nets,lod_tensor,average,
debugger,communicator,evaluator,input}.py and the reference op kernels
cited per test.
"""
import numpy as np
import pytest

import paddle_tpu as fluid


def _run_single_op(op_type, feeds, outputs, attrs, fetch,
                   var_shapes=None):
    prog, startup = fluid.Program(), fluid.Program()
    blk = prog.global_block()
    for name, arr in feeds.items():
        v = blk.create_var(name=name, dtype=str(np.asarray(arr).dtype))
        v.shape = tuple(np.asarray(arr).shape)
        v.is_data = True
    out_vars = {}
    for slot, names in outputs.items():
        out_vars[slot] = names
        for n in names:
            blk.create_var(name=n, dtype="float32")
    blk.append_op(op_type,
                  {k: [k] for k in feeds},
                  out_vars, dict(attrs), infer_shape=False)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        outs = exe.run(prog, feed=feeds, fetch_list=fetch,
                       return_numpy=False)
    return [np.asarray(o.array if hasattr(o, "array") else o)
            for o in outs]


class TestNets:
    def test_simple_img_conv_pool(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            img = fluid.layers.data("img", shape=[1, 28, 28],
                                    dtype="float32")
            out = fluid.nets.simple_img_conv_pool(
                img, num_filters=4, filter_size=5, pool_size=2,
                pool_stride=2, act="relu")
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            (o,) = exe.run(prog,
                           feed={"img": np.random.rand(2, 1, 28, 28)
                                 .astype("float32")},
                           fetch_list=[out])
        assert np.asarray(o).shape == (2, 4, 12, 12)

    def test_img_conv_group_vgg_block(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            img = fluid.layers.data("img", shape=[3, 16, 16],
                                    dtype="float32")
            out = fluid.nets.img_conv_group(
                img, conv_num_filter=[8, 8], pool_size=2,
                conv_act="relu", conv_with_batchnorm=True,
                pool_stride=2)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            (o,) = exe.run(prog,
                           feed={"img": np.random.rand(2, 3, 16, 16)
                                 .astype("float32")},
                           fetch_list=[out])
        assert np.asarray(o).shape == (2, 8, 8, 8)

    def test_glu_halves_dim(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            out = fluid.nets.glu(x, dim=-1)
        exe = fluid.Executor(fluid.CPUPlace())
        xv = np.random.rand(2, 8).astype("float32")
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            (o,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
        a, b = xv[:, :4], xv[:, 4:]
        np.testing.assert_allclose(np.asarray(o),
                                   a / (1 + np.exp(-b)), rtol=1e-5)

    def test_scaled_dot_product_attention(self):
        exe = fluid.Executor(fluid.CPUPlace())
        prog2, startup2 = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog2, startup2):
            q3 = fluid.layers.data("q", shape=[2, 5, 8],
                                   dtype="float32",
                                   append_batch_size=False)
            out3 = fluid.nets.scaled_dot_product_attention(
                q3, q3, q3, num_heads=2)
        scope = fluid.Scope()
        qv3 = np.random.rand(2, 5, 8).astype("float32")
        with fluid.scope_guard(scope):
            exe.run(startup2)
            (o,) = exe.run(prog2, feed={"q": qv3}, fetch_list=[out3])
        o = np.asarray(o)
        assert o.shape == (2, 5, 8)
        # numpy reference, per head
        d = 4
        ref = np.zeros_like(qv3)
        for h in range(2):
            qh = qv3[:, :, h * d:(h + 1) * d]
            logits = (qh / np.sqrt(d)) @ qh.transpose(0, 2, 1)
            w = np.exp(logits - logits.max(-1, keepdims=True))
            w = w / w.sum(-1, keepdims=True)
            ref[:, :, h * d:(h + 1) * d] = w @ qh
        np.testing.assert_allclose(o, ref, rtol=1e-4, atol=1e-5)

    def test_sequence_conv_pool(self):
        from paddle_tpu.lod_tensor import create_lod_tensor

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[6], dtype="float32",
                                  lod_level=1)
            out = fluid.nets.sequence_conv_pool(x, num_filters=4,
                                                filter_size=3)
        exe = fluid.Executor(fluid.CPUPlace())
        data = create_lod_tensor(
            np.random.rand(7, 6).astype("float32"), [[3, 4]])
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            (o,) = exe.run(prog, feed={"x": data}, fetch_list=[out])
        assert np.asarray(o).shape == (2, 4)  # one row per sequence


class TestLodTensorHelpers:
    def test_create_lod_tensor(self):
        t = fluid.create_lod_tensor(
            np.arange(10).reshape(10, 1).astype("int64"), [[4, 6]])
        assert t.lod() == [[0, 4, 10]]

    def test_length_mismatch_raises(self):
        with pytest.raises(ValueError):
            fluid.create_lod_tensor(
                np.zeros((5, 1), "int64"), [[4, 6]])

    def test_random_int_lodtensor(self):
        t = fluid.create_random_int_lodtensor(
            [[2, 3]], base_shape=[1], low=0, high=9)
        arr = np.asarray(t.array)
        assert arr.shape == (5, 1)
        assert arr.min() >= 0 and arr.max() <= 9


class TestWeightedAverage:
    def test_weighted_mean(self):
        wa = fluid.average.WeightedAverage()
        wa.add(value=2.0, weight=1)
        wa.add(value=4.0, weight=3)
        assert abs(wa.eval() - (2 + 12) / 4) < 1e-9
        wa.reset()
        with pytest.raises(ValueError):
            wa.eval()


class TestDebugger:
    def test_pprint_and_dot(self, tmp_path):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.fc(x, size=2, act="relu")
        text = fluid.debugger.pprint_program_codes(prog)
        assert "fc" in text or "mul" in text
        path = fluid.debugger.draw_block_graphviz(
            prog.global_block(), path=str(tmp_path / "g.dot"))
        content = open(path).read()
        assert "digraph" in content and "->" in content


class TestCommunicator:
    def test_async_send_batches_through_communicator(self):
        from paddle_tpu.ops.distributed_ops import reset_emulated_servers

        reset_emulated_servers()
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(x, 1, bias_attr=False)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.01).minimize(loss)
        t = fluid.DistributeTranspiler()
        t.transpile(trainer_id=0, program=prog, startup_program=startup,
                    pservers="ps0:6174", trainers=1, sync_mode=False)
        scope = fluid.Scope()
        comm = fluid.Communicator(prog, mode="ASYNC", send_wait_ms=2)
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            psprog = t.get_pserver_program("ps0:6174")
            exe.run(t.get_startup_program("ps0:6174", psprog))
            exe.run(psprog)
            exe.run(startup)
            comm.start()
            assert comm.is_running()
            rng = np.random.RandomState(0)
            W = rng.randn(8, 1).astype("float32")
            losses = []
            try:
                for i in range(80):
                    xb = rng.randn(16, 8).astype("float32")
                    (l,) = exe.run(t.get_trainer_program(),
                                   feed={"x": xb, "y": xb @ W},
                                   fetch_list=[loss])
                    losses.append(float(np.asarray(l).ravel()[0]))
            finally:
                comm.stop()
        assert not comm.is_running()
        assert comm.pushes > 0  # the background flusher delivered
        # async updates are stale/racy by design — compare WINDOWS
        head = float(np.mean(losses[:10]))
        tail = float(np.mean(losses[-10:]))
        assert tail < 0.5 * head, (head, tail)


class TestEvaluators:
    def test_chunk_evaluator_accumulates(self):
        from paddle_tpu.lod_tensor import create_lod_tensor

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            inf = fluid.layers.data("inf", shape=[1], dtype="int64",
                                    lod_level=1)
            lab = fluid.layers.data("lab", shape=[1], dtype="int64",
                                    lod_level=1)
            ev = fluid.evaluator.ChunkEvaluator(
                inf, lab, chunk_scheme="IOB", num_chunk_types=3)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            # sequence: infer chunks {(0,1,0),(3,4,1)}; label {(0,1,0),(3,3,1)}
            infer = create_lod_tensor(np.array(
                [[0], [1], [6], [2], [3]], "int64"), [[5]])
            label = create_lod_tensor(np.array(
                [[0], [1], [6], [2], [6]], "int64"), [[5]])
            for _ in range(2):  # two identical batches accumulate
                exe.run(prog, feed={"inf": infer, "lab": label},
                        fetch_list=[])
            p, r, f1 = ev.eval(exe)
        assert abs(float(p[0]) - 0.5) < 1e-6
        assert abs(float(r[0]) - 0.5) < 1e-6
        assert abs(float(f1[0]) - 0.5) < 1e-6


class TestOpTail:
    def test_chunk_eval_op_iob(self):
        from paddle_tpu.lod_tensor import create_lod_tensor

        infer = create_lod_tensor(np.array(
            [[0], [1], [6], [2], [3]], "int64"), [[5]])
        label = create_lod_tensor(np.array(
            [[0], [1], [6], [2], [6]], "int64"), [[5]])
        outs = _run_single_op(
            "chunk_eval", {"Inference": infer, "Label": label},
            {"Precision": ["p"], "Recall": ["r"], "F1-Score": ["f"],
             "NumInferChunks": ["ni"], "NumLabelChunks": ["nl"],
             "NumCorrectChunks": ["nc"]},
            {"num_chunk_types": 3, "chunk_scheme": "IOB",
             "excluded_chunk_types": []},
            ["p", "r", "f", "ni", "nl", "nc"])
        p, r, f, ni, nl, nc = [o.reshape(-1)[0] for o in outs]
        assert (p, r, f) == (0.5, 0.5, 0.5)
        assert (ni, nl, nc) == (2, 2, 1)

    def test_chunk_eval_dense_with_seq_length(self):
        """Dense [B, T] inputs truncate per-row at SeqLength
        (reference chunk_eval_op.h:181) — padding must not count."""
        # row 0 (len 2): infer B-0 I-0 | label B-0 I-0 -> 1 correct
        # row 1 (len 1): infer B-1     | label B-0     -> 0 correct
        # padding (6 = Other) would create spurious chunks if counted
        infer = np.array([[0, 1, 6], [2, 6, 6]], "int64")
        label = np.array([[0, 1, 6], [0, 6, 6]], "int64")
        outs = _run_single_op(
            "chunk_eval",
            {"Inference": infer, "Label": label,
             "SeqLength": np.array([2, 1], "int64")},
            {"Precision": ["p"], "Recall": ["r"], "F1-Score": ["f"],
             "NumInferChunks": ["ni"], "NumLabelChunks": ["nl"],
             "NumCorrectChunks": ["nc"]},
            {"num_chunk_types": 3, "chunk_scheme": "IOB",
             "excluded_chunk_types": []},
            ["ni", "nl", "nc"])
        ni, nl, nc = [int(o.reshape(-1)[0]) for o in outs]
        assert (ni, nl, nc) == (2, 2, 1)

    def test_weighted_average_elementwise(self):
        wa = fluid.average.WeightedAverage()
        wa.add(np.array([1.0, 3.0]), weight=1)
        wa.add(np.array([3.0, 5.0]), weight=1)
        np.testing.assert_allclose(wa.eval(), [2.0, 4.0])

    def test_positive_negative_pair(self):
        outs = _run_single_op(
            "positive_negative_pair",
            {"Score": np.array([[3.], [2.], [1.]], "float32"),
             "Label": np.array([[1.], [0.], [2.]], "float32"),
             "QueryID": np.array([[0], [0], [0]], "int64")},
            {"PositivePair": ["pos"], "NegativePair": ["neg"],
             "NeutralPair": ["neu"]},
            {"column": 0}, ["pos", "neg", "neu"])
        pos, neg, neu = [float(o.reshape(-1)[0]) for o in outs]
        assert (pos, neg, neu) == (1.0, 2.0, 0.0)

    def test_sequence_enumerate(self):
        from paddle_tpu.lod_tensor import create_lod_tensor

        x = create_lod_tensor(
            np.array([[1], [2], [3], [4]], "int64"), [[4]])
        (out,) = _run_single_op(
            "sequence_enumerate", {"X": x}, {"Out": ["out"]},
            {"win_size": 2, "pad_value": 0}, ["out"])
        np.testing.assert_array_equal(
            out, [[1, 2], [2, 3], [3, 4], [4, 0]])

    def test_sequence_erase(self):
        from paddle_tpu.lod_tensor import create_lod_tensor

        x = create_lod_tensor(
            np.array([[2], [1], [3], [1], [5]], "int64"), [[3, 2]])
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            xv = fluid.layers.data("x", shape=[1], dtype="int64",
                                   lod_level=1)
            out = fluid.layers.sequence_erase(xv, tokens=[1])
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            (o,) = exe.run(prog, feed={"x": x}, fetch_list=[out],
                           return_numpy=False)
        np.testing.assert_array_equal(np.asarray(o.array).reshape(-1),
                                      [2, 3, 5])
        assert o.lod() == [[0, 2, 3]]

    def test_sequence_erase_keeps_upper_lod_levels(self):
        from paddle_tpu.core.tensor import LoDTensor

        x = LoDTensor(np.array([[2], [1], [3], [1], [5]], "int64"))
        x.set_lod([[0, 1, 2], [0, 3, 5]])  # 2 level-0 groups
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            xv = fluid.layers.data("x", shape=[1], dtype="int64",
                                   lod_level=2)
            out = fluid.layers.sequence_erase(xv, tokens=[1])
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            (t,) = exe.run(prog, feed={"x": x}, fetch_list=[out],
                           return_numpy=False)
        assert t.lod() == [[0, 1, 2], [0, 2, 3]]

    def test_proximal_adagrad(self):
        p = np.array([1.0, -2.0], "float32")
        g = np.array([0.5, 0.25], "float32")
        m = np.array([0.1, 0.1], "float32")
        lr = np.array([0.1], "float32")
        outs = _run_single_op(
            "proximal_adagrad",
            {"Param": p, "Moment": m, "Grad": g, "LearningRate": lr},
            {"ParamOut": ["Param"], "MomentOut": ["Moment"]},
            {"l1": 0.01, "l2": 0.1}, ["Param", "Moment"])
        m_ref = m + g * g
        prox = p - 0.1 * g / np.sqrt(m_ref)
        p_ref = np.sign(prox) * np.maximum(
            np.abs(prox) - 0.1 * 0.01, 0) / (1 + 0.1 * 0.1)
        np.testing.assert_allclose(outs[0], p_ref, rtol=1e-5)
        np.testing.assert_allclose(outs[1], m_ref, rtol=1e-6)

    def test_dgc_momentum_switches_at_rampup(self):
        p = np.array([1.0, 1.0], "float32")
        g = np.array([0.2, 0.4], "float32")
        v = np.array([0.1, 0.1], "float32")
        lr = np.array([0.5], "float32")
        nranks = np.array([2.0], "float32")
        for step, expect_momentum in ((0.0, True), (10.0, False)):
            outs = _run_single_op(
                "dgc_momentum",
                {"Param": p, "Grad": g, "Velocity": v,
                 "LearningRate": lr,
                 "current_step": np.array([step], "float32"),
                 "nranks": nranks},
                {"ParamOut": ["Param"], "VelocityOut": ["Velocity"],
                 "Grad_out": ["Gout"]},
                {"mu": 0.9, "rampup_begin_step": 5.0},
                ["Param", "Velocity", "Gout"])
            gs = g / 2.0
            if expect_momentum:
                v_ref = 0.9 * v + gs
                p_ref = p - 0.5 * v_ref
            else:
                v_ref = v
                p_ref = p - 0.5 * gs
            np.testing.assert_allclose(outs[0], p_ref, rtol=1e-5)
            np.testing.assert_allclose(outs[1], v_ref, rtol=1e-5)
            np.testing.assert_allclose(outs[2], gs, rtol=1e-6)

    def test_dgc_clip_by_norm_gated(self):
        x = np.array([3.0, 4.0], "float32")  # norm 5
        for step, clipped in ((0.0, False), (10.0, True)):
            (out,) = _run_single_op(
                "dgc_clip_by_norm",
                {"X": x, "current_step": np.array([step], "float32")},
                {"Out": ["out"]},
                {"max_norm": 1.0, "rampup_begin_step": 5.0}, ["out"])
            ref = x / 5.0 if clipped else x
            np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_ref_by_trainer_id(self):
        prog, startup = fluid.Program(), fluid.Program()
        blk = prog.global_block()
        for n, val in (("a", 1.0), ("b", 2.0)):
            v = blk.create_var(name=n, dtype="float32")
            v.shape = (2,)
            v.is_data = True
        tid = blk.create_var(name="tid", dtype="int64")
        tid.shape = (1,)
        tid.is_data = True
        out = blk.create_var(name="out", dtype="float32")
        blk.append_op("ref_by_trainer_id",
                      {"X": ["a", "b"], "TrainerId": ["tid"]},
                      {"Out": ["out"]}, {}, infer_shape=False)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            (o,) = exe.run(prog, feed={
                "a": np.full((2,), 1.0, "float32"),
                "b": np.full((2,), 2.0, "float32"),
                "tid": np.array([1], "int64")}, fetch_list=["out"])
        np.testing.assert_array_equal(np.asarray(o), [2.0, 2.0])


class TestInputModule:
    def test_one_hot_and_embedding(self):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            ids = fluid.layers.data("ids", shape=[1], dtype="int64")
            oh = fluid.input.one_hot(ids, depth=4)
            emb = fluid.input.embedding(ids, size=(10, 3))
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            o, e = exe.run(prog,
                           feed={"ids": np.array([[1], [3]], "int64")},
                           fetch_list=[oh, emb])
        o = np.asarray(o)
        assert o.shape[-1] == 4 and o.reshape(2, 4)[0, 1] == 1.0
        assert np.asarray(e).reshape(2, 3).shape == (2, 3)
