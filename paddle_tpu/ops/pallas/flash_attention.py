"""Flash attention as a Pallas TPU kernel.

Parity intent: the reference hand-fuses attention for inference in CUDA
(operators/fused/multihead_matmul_op.cu, math/bert_encoder_functor.cu);
this is the TPU-native equivalent, done the flash way so the S x S
score matrix never materializes in HBM:

- grid = (batch*heads, q_blocks, k_blocks) with the K dimension
  iterated sequentially ("arbitrary") so the running-softmax scratch
  (m, l, acc in VMEM) persists across K steps;
- each step does two MXU matmuls (Q@K^T, P@V) on [block_q, block_k]
  tiles streamed HBM->VMEM by pallas;
- the log-sum-exp accumulation is float32 regardless of input dtype.

Backward: dense-recompute VJP via jax.custom_vjp (exact; a pallas
backward kernel is a later optimization — the forward is where
inference/serving time goes).

Off-TPU the public entry falls back to the identical dense math, so
programs are portable and CI (CPU) still exercises the call sites.

Numerics, measured on v5e: with float32 inputs both this kernel and
XLA's dense attention run the MXU's default (bfloat16-pass) precision;
against an fp64 oracle the kernel's max error is ~2e-3 (non-causal) /
~8e-3 (causal) and the dense path's is ~3e-3 / ~1e-2 — the flash
accumulation is slightly MORE accurate, and the two agree within their
mutual rounding. Tests compare in interpret mode on CPU where the
math is exact.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _dense_attention(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        S = q.shape[2]
        pos = jnp.arange(S)
        s = jnp.where((pos[:, None] >= pos[None, :])[None, None], s,
                      NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale, causal, block_q, block_k, nk):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32) * scale          # [bq, d]
    k = k_ref[0].astype(jnp.float32)                  # [bk, d]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [bq, bk]
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)

    m_prev = m_ref[:]                                 # [bq, 1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new)                            # [bq, bk]
    alpha = jnp.exp(m_prev - m_new)                   # [bq, 1]
    l_ref[:] = l_ref[:] * alpha + p.sum(axis=1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())))
    m_ref[:] = m_new

    @pl.when(ki == nk - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] /
                    jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, D = q.shape
    bq = min(block_q, S)
    bk = min(block_k, S)
    if S % bq or S % bk:
        return _dense_attention(q, k, v, causal, scale)
    nq, nk = S // bq, S // bk
    q3 = q.reshape(B * H, S, D)
    k3 = k.reshape(B * H, S, D)
    v3 = v.reshape(B * H, S, D)

    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk, nk=nk)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(B, H, S, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                         interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _dense_attention(q, k, v, causal,
                                                      scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, force_pallas: bool = False):
    """Flash attention over ``[B, H, S, D]`` tensors.

    Uses the pallas kernel on TPU backends (or when ``force_pallas`` —
    interpret mode — is requested, e.g. in tests); dense math elsewhere.
    """
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    backend = jax.default_backend()
    if backend == "tpu":
        return _flash(q, k, v, causal, scale, block_q, block_k, False)
    if force_pallas:
        return _flash(q, k, v, causal, scale, block_q, block_k, True)
    return _dense_attention(q, k, v, causal, scale)
