"""Distributed sparse tables (pslib path): embedding(is_distributed=True)
row-sliced across pservers.

The transpiler swaps the lookup for a sparse pull
(distributed_lookup_table), the grad for a sparse push that the hosting
server applies via its optimizer sub-block, and drops the trainer-side
optimizer op. Reference contract:
operators/distributed_ops/distributed_lookup_table_op.cc +
fleet_wrapper.h:84 (PullSparseVarsSync/PushSparseVarsAsync).

This file covers the in-process emulated transport; the real 2-pserver
multi-process run lives in test_multiprocess_sparse_ps.py.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.ops.distributed_ops import (_EMULATED_SERVERS,
                                            reset_emulated_servers)

V, D, N = 10, 4, 6
EPS = ["local://tbl-a", "local://tbl-b"]


def _build(is_distributed):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data(name="ids", shape=[N, 1], dtype="int64")
        tgt = fluid.data(name="tgt", shape=[N, D], dtype="float32")
        emb = fluid.layers.embedding(
            ids, size=[V, D], is_distributed=is_distributed,
            param_attr=fluid.ParamAttr(name="table"))
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(fluid.layers.elementwise_sub(emb, tgt)))
        fluid.optimizer.SGDOptimizer(0.5).minimize(loss)
    return main, startup, loss


def test_transpiled_ops_and_row_slicing():
    main, startup, _ = _build(True)
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers=",".join(EPS), trainers=1)
    types = [op.type for op in main.global_block().ops]
    assert "distributed_lookup_table" in types
    assert "distributed_push_sparse" in types
    assert "lookup_table" not in types
    assert "sgd" not in types  # table update moved server-side
    assert t.dist_tables["table"]["starts"] == [0, 5]
    assert t.dist_tables["table"]["counts"] == [5, 5]
    # each server program hosts ITS row slice
    for k, ep in enumerate(EPS):
        ps = t.get_pserver_program(ep)
        v = ps.global_block()._find_var_recursive("table")
        assert tuple(v.shape) == (5, D)
        lsv = ps.global_block().ops[-1]
        assert any(e.startswith("table@GRAD")
                   for e in lsv.attrs["grad_to_block_id"])


def test_emulated_sparse_table_matches_dense_oracle():
    """One training step against two emulated pservers == the dense
    single-process step, slice by slice."""
    reset_emulated_servers()
    main, startup, loss = _build(True)
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers=",".join(EPS), trainers=1)

    rng = np.random.RandomState(0)
    table0 = rng.randn(V, D).astype("float32")
    feed = {"ids": rng.randint(0, V, (N, 1)).astype("int64"),
            "tgt": rng.randn(N, D).astype("float32")}

    # boot both pservers (emulated: listen_and_serv registers + returns)
    import jax.numpy as jnp

    server_scopes = {}
    for k, ep in enumerate(EPS):
        ps = t.get_pserver_program(ep)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(t.get_startup_program(ep, ps))
            s, c = (t.dist_tables["table"]["starts"][k],
                    t.dist_tables["table"]["counts"][k])
            scope.var("table").get_tensor()._array = jnp.asarray(
                table0[s:s + c])
            exe.run(ps)
        server_scopes[ep] = scope

    # trainer step
    tr_scope = fluid.Scope()
    with fluid.scope_guard(tr_scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (l_dist,) = exe.run(main, feed=feed, fetch_list=[loss])

    # dense oracle
    main_d, startup_d, loss_d = _build(False)
    o_scope = fluid.Scope()
    with fluid.scope_guard(o_scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup_d)
        o_scope.var("table").get_tensor()._array = jnp.asarray(table0)
        (l_dense,) = exe.run(main_d, feed=feed, fetch_list=[loss_d])
        table_dense = np.asarray(o_scope.find_var("table").raw().array)

    assert abs(float(np.ravel(l_dist)[0])
               - float(np.ravel(l_dense)[0])) < 1e-6
    for k, ep in enumerate(EPS):
        s, c = (t.dist_tables["table"]["starts"][k],
                t.dist_tables["table"]["counts"][k])
        got = np.asarray(
            server_scopes[ep].find_var("table").raw().array)
        np.testing.assert_allclose(got, table_dense[s:s + c],
                                   rtol=1e-6, atol=1e-7)
    reset_emulated_servers()
