"""LoD beam_search / beam_search_decode ops — fixtures and expected
outputs lifted from the reference unit tests
(tests/unittests/test_beam_search_op.py, test_beam_search_decode_op.py)
so the host kernels match the C++ functors bit-for-bit.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.tensor import LoDTensor


def _run_beam_search_step():
    prog, startup = fluid.Program(), fluid.Program()
    blk = prog.global_block()
    for n, dt in [("pre_ids", "int64"), ("pre_scores", "float32"),
                  ("ids", "int64"), ("scores", "float32")]:
        blk.create_var(name=n, dtype=dt)
    sel_i = blk.create_var(name="selected_ids", dtype="int64")
    sel_s = blk.create_var(name="selected_scores", dtype="float32")
    par = blk.create_var(name="parent_idx", dtype="int32")
    blk.append_op("beam_search",
                  inputs={"pre_ids": ["pre_ids"],
                          "pre_scores": ["pre_scores"],
                          "ids": ["ids"], "scores": ["scores"]},
                  outputs={"selected_ids": ["selected_ids"],
                          "selected_scores": ["selected_scores"],
                          "parent_idx": ["parent_idx"]},
                  attrs={"level": 0, "beam_size": 2, "end_id": 0,
                         "is_accumulated": True},
                  infer_shape=False)
    scope = fluid.Scope()
    lod = [[0, 2, 4], [0, 1, 2, 3, 4]]
    scope.var("pre_ids").get_tensor().set(
        np.array([[1, 2, 3, 4]], "int64"))
    scope.var("pre_scores").get_tensor().set(
        np.array([[0.1, 0.2, 0.3, 0.4]], "float32"))
    t = scope.var("ids").get_tensor()
    t.set(np.array([[4, 2, 5], [2, 1, 3], [3, 5, 2], [8, 2, 1]], "int64"))
    t._lod = [list(l) for l in lod]
    t = scope.var("scores").get_tensor()
    t.set(np.array([[0.5, 0.3, 0.2], [0.6, 0.3, 0.1],
                    [0.9, 0.5, 0.1], [0.7, 0.5, 0.1]], "float32"))
    t._lod = [list(l) for l in lod]
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(prog, fetch_list=[])
    return scope


def test_beam_search_op_reference_fixture():
    scope = _run_beam_search_step()
    sel_ids = scope.find_var("selected_ids").get_tensor()
    sel_scores = scope.find_var("selected_scores").get_tensor()
    parent = scope.find_var("parent_idx").get_tensor().numpy()
    np.testing.assert_array_equal(
        sel_ids.numpy(), np.array([4, 2, 3, 8])[:, None])
    np.testing.assert_allclose(
        sel_scores.numpy(), np.array([0.5, 0.6, 0.9, 0.7])[:, None])
    assert sel_ids.lod() == [[0, 2, 4], [0, 1, 2, 3, 4]]
    np.testing.assert_array_equal(parent, [0, 1, 2, 3])


def test_beam_search_decode_op_reference_fixture():
    prog, startup = fluid.Program(), fluid.Program()
    blk = prog.global_block()
    blk.create_var(name="ids")
    blk.create_var(name="scores")
    blk.create_var(name="sentence_ids", dtype="int64")
    blk.create_var(name="sentence_scores", dtype="float32")
    blk.append_op("beam_search_decode",
                  inputs={"Ids": ["ids"], "Scores": ["scores"]},
                  outputs={"SentenceIds": ["sentence_ids"],
                           "SentenceScores": ["sentence_scores"]},
                  attrs={"beam_size": 2, "end_id": 1},
                  infer_shape=False)
    scope = fluid.Scope()
    ids_arr = scope.var("ids").get_lod_tensor_array()
    scores_arr = scope.var("scores").get_lod_tensor_array()
    steps = [
        ([[0, 1, 2], [0, 1, 2]], [0, 0]),
        ([[0, 1, 2], [0, 2, 4]], [2, 3, 4, 5]),
        ([[0, 2, 4], [0, 2, 2, 4, 4]], [3, 1, 5, 4]),
        ([[0, 2, 4], [0, 1, 2, 3, 4]], [1, 1, 3, 5]),
        ([[0, 2, 4], [0, 0, 0, 2, 2]], [5, 1]),
    ]
    for lod, data in steps:
        for arr, dt in ((ids_arr, "int64"), (scores_arr, "float32")):
            t = LoDTensor()
            t.set(np.array(data, dt))
            t._lod = [list(l) for l in lod]
            arr.append(t)
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(prog, fetch_list=[])
    si = scope.find_var("sentence_ids").get_tensor()
    ss = scope.find_var("sentence_scores").get_tensor()
    expected_lod = [[0, 2, 4], [0, 4, 7, 12, 17]]
    expected = np.array(
        [0, 2, 3, 1, 0, 2, 1, 0, 4, 5, 3, 5, 0, 4, 5, 3, 1], "int64")
    assert si.lod() == expected_lod
    assert ss.lod() == expected_lod
    np.testing.assert_array_equal(si.numpy().reshape(-1), expected)
    np.testing.assert_allclose(ss.numpy().reshape(-1),
                               expected.astype("float32"))
