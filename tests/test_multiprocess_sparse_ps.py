"""Multi-process distributed sparse tables (round-4 VERDICT item #4):
Wide&Deep with its embedding tables row-sliced across TWO real pserver
OS processes over the socket RPC; the trainer process pulls rows,
trains to convergence, and pushes sparse grads that each server applies
through its optimizer sub-block.

Reference contract: fleet_wrapper.h:84-156 + dist_ctr.py (the CTR
north-star) trained through test_dist_fleet_base-style localhost
subprocesses.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dist_worker_sparse_ps.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env(role, endpoints, my_ep=""):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("XLA_FLAGS", None)
    env["PADDLE_TRAINING_ROLE"] = role
    env["PSERVER_ENDPOINTS"] = endpoints
    if my_ep:
        env["PSERVER_ENDPOINT"] = my_ep
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def test_wide_deep_trains_over_two_sparse_pservers(tmp_path):
    eps = ["127.0.0.1:%d" % _free_port(), "127.0.0.1:%d" % _free_port()]
    endpoints = ",".join(eps)
    out = tmp_path / "trainer.json"

    servers = [
        subprocess.Popen(
            [sys.executable, WORKER, str(tmp_path / ("ps%d" % i))],
            env=_env("PSERVER", endpoints, ep),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i, ep in enumerate(eps)
    ]
    try:
        tr = subprocess.run([sys.executable, WORKER, str(out)],
                            env=_env("TRAINER", endpoints),
                            capture_output=True, text=True, timeout=300)
        assert tr.returncode == 0, tr.stderr[-3000:]
        res = json.loads(out.read_text())
        losses = res["losses"]
        assert all(np.isfinite(l) for l in losses), losses
        # convergence: the id->label correlation is learnable
        assert losses[-1] < losses[0] * 0.8, losses
        # BOTH pservers host live, trained slices
        assert len(res["slice_sums"]) == 2
        assert all(s > 0 for s in res["slice_sums"]), res["slice_sums"]
        for p in servers:
            p.wait(timeout=60)
    finally:
        for p in servers:
            if p.poll() is None:
                p.kill()
