"""fluid.layers — graph-building API surface.

Parity: /root/reference/python/paddle/fluid/layers/ (~290 public APIs
across nn.py, tensor.py, loss.py, control_flow.py, ops.py, metric_op.py,
collective.py, sequence_lod.py, rnn.py, detection.py).
"""
from .nn import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .metric_op import *  # noqa: F401,F403
from .sequence_lod import *  # noqa: F401,F403
from .rnn import *  # noqa: F401,F403
from .learning_rate_scheduler import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from ..distribution import (  # noqa: F401
    Categorical,
    MultivariateNormalDiag,
    Normal,
    Uniform,
)
from .detection import *  # noqa: F401,F403
from .io import data  # noqa: F401
from . import math_op_patch  # noqa: F401  (patches Variable operators)

from .nn import __all__ as _nn_all
from .tensor import __all__ as _tensor_all
from .loss import __all__ as _loss_all
from .ops import __all__ as _ops_all
from .control_flow import __all__ as _cf_all
from .metric_op import __all__ as _metric_all
from .sequence_lod import __all__ as _seq_all
from .rnn import __all__ as _rnn_all
from .learning_rate_scheduler import __all__ as _lrs_all
from .extras import __all__ as _extras_all
from .detection import __all__ as _det_all

__all__ = (
    ["data"]
    + _nn_all
    + _tensor_all
    + _loss_all
    + _ops_all
    + _cf_all
    + _metric_all
    + _seq_all
    + _rnn_all
    + _det_all
    + _lrs_all
    + _extras_all
    + ["Categorical", "MultivariateNormalDiag", "Normal", "Uniform"]
)
