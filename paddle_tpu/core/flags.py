"""Runtime flag system.

Parity: /root/reference/paddle/fluid/platform/flags.cc (~40 gflags) +
pybind global_value_getter_setter (fluid.get_flags/set_flags) + the
FLAGS_* env-var init tier (pybind.cc:1484 init_gflags). Flags that
steered CUDA/allocator machinery XLA now owns are accepted for script
compatibility and marked no-op below.
"""
from __future__ import annotations

import os
from typing import Dict, List, Union

# name -> (default, doc). "(no-op)" = subsumed by XLA/JAX.
_DEFS = {
    "FLAGS_check_nan_inf": (False, "scan op outputs for nan/inf "
                            "(reference operator.cc:1032)"),
    "FLAGS_benchmark": (False, "sync + time every op (no-op)"),
    "FLAGS_eager_delete_tensor_gb": (-1.0, "eager var deletion in the "
                                     "interpreter when >= 0; compiled "
                                     "programs rely on XLA buffer "
                                     "liveness instead"),
    "FLAGS_fraction_of_gpu_memory_to_use": (0.92, "allocator fraction "
                                            "(no-op)"),
    "FLAGS_allocator_strategy": ("auto_growth", "allocator choice "
                                 "(no-op)"),
    "FLAGS_cudnn_deterministic": (False, "deterministic conv: maps to "
                                  "XLA deterministic ops"),
    "FLAGS_paddle_num_threads": (1, "CPU math threads (no-op)"),
    "FLAGS_use_mkldnn": (False, "MKLDNN kernels (no-op)"),
    "FLAGS_selected_gpus": ("", "visible devices (use JAX platform env)"),
    "FLAGS_enable_parallel_graph": (False, "executor choice (no-op)"),
    "FLAGS_max_inplace_grad_add": (0, "grad-add inplace (no-op)"),
    "FLAGS_use_pallas_conv": ("off", "route NHWC convs to the pallas "
                              "implicit-GEMM kernel: off | auto (only "
                              "the measured-win shape class: expansion "
                              "1x1) | all (every viable shape)"),
    "FLAGS_dygraph_lazy": (False, "queue eager dygraph ops and flush "
                           "them as one compiled dispatch per step "
                           "(lazy-tensor mode, dygraph/lazy.py)"),
    "FLAGS_tpu_metrics": (False, "arm the runtime observability layer "
                          "(paddle_tpu/observability: metrics registry "
                          "+ span tracing across every execution "
                          "path). Env alias: PADDLE_TPU_METRICS"),
}

# secondary env names honored at init (the primary is FLAGS_<name>)
_ENV_ALIASES = {
    "FLAGS_tpu_metrics": "PADDLE_TPU_METRICS",
}

_values: Dict[str, object] = {}


def _coerce(default, raw: str):
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


def _init_from_env():
    for name, (default, _doc) in _DEFS.items():
        raw = os.environ.get(name)
        if raw is None and name in _ENV_ALIASES:
            raw = os.environ.get(_ENV_ALIASES[name])
        _values[name] = _coerce(default, raw) if raw is not None else default


_init_from_env()


def _norm(name: str) -> str:
    return name if name.startswith("FLAGS_") else "FLAGS_" + name


def get_flags(flags: Union[str, List[str]]):
    """fluid.get_flags (reference pybind global_value_getter_setter)."""
    single = isinstance(flags, str)
    names = [flags] if single else list(flags)
    out = {}
    for n in names:
        key = _norm(n)
        if key not in _values:
            raise ValueError("unknown flag %r" % n)
        out[key] = _values[key]
    return out


def set_flags(flags: Dict[str, object]):
    """fluid.set_flags."""
    for n, v in flags.items():
        key = _norm(n)
        if key not in _values:
            raise ValueError("unknown flag %r" % n)
        default = _DEFS[key][0]
        _values[key] = _coerce(default, v) if isinstance(v, str) else \
            type(default)(v) if not isinstance(default, str) else str(v)
        if key == "FLAGS_tpu_metrics":
            # keep the observability layer's fast-path bool in sync
            from .. import observability

            (observability.enable if _values[key]
             else observability.disable)()


def flag(name: str):
    """Internal fast read."""
    return _values[_norm(name)]
