"""Dygraph optimizer step: apply registry optimizer ops eagerly.

Reference flow: loss.backward() fills grads; optimizer.minimize runs the
optimizer op per parameter eagerly (optimizer.py _append_optimize_op via
tracer). Accumulator state lives on the optimizer as VarBase arrays.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ..core.registry import BOUND_OUTPUTS_ATTR, OpInfoMap
from .varbase import VarBase


def _get_state(opt, pname, key, like, fill=0.0, shape=None):
    store: Dict = opt._dygraph_state
    k = "%s_%s" % (pname, key)
    v = store.get(k)
    if v is None:
        import jax.numpy as jnp

        if shape is not None:
            arr = jnp.full(tuple(shape), fill, dtype=like._array.dtype)
        else:
            arr = jnp.full(like._array.shape, fill, dtype=like._array.dtype)
        v = VarBase(arr, name=k, stop_gradient=True, persistable=True)
        store[k] = v
    return v


_OPT_SPECS = {
    # optimizer class name -> (op type, state slots builder, attr builder)
}


def dygraph_minimize(opt, loss, parameter_list=None):
    import jax.numpy as jnp

    from .tracer import current_tracer

    tracer = current_tracer()
    if loss is not None and all(
            rec is not None for rec in [tracer]) and not tracer.tape:
        loss.backward()
    params = parameter_list or tracer.all_parameters()
    lr = opt.current_step_lr
    if not isinstance(lr, float):
        lr = float(np.asarray(lr() if callable(lr) else lr).reshape(()))
    lr_arr = jnp.asarray([lr], dtype=jnp.float32)
    infos = OpInfoMap.instance()

    name = type(opt).__name__
    for p in params:
        if p._grad is None or not getattr(p, "trainable", True):
            continue
        g = p._grad
        ins = {"Param": p._array, "Grad": g, "LearningRate": lr_arr}
        if name in ("SGDOptimizer", "SGD"):
            op_type, attrs = "sgd", {}
        elif name in ("MomentumOptimizer", "Momentum"):
            vel = _get_state(opt, p.name, "velocity", p)
            ins["Velocity"] = vel._array
            op_type = "momentum"
            attrs = {"mu": opt._momentum, "use_nesterov": opt._use_nesterov}
        elif name in ("AdamOptimizer", "Adam", "AdamW", "LambOptimizer"):
            m1 = _get_state(opt, p.name, "moment1", p)
            m2 = _get_state(opt, p.name, "moment2", p)
            b1p = _get_state(opt, p.name, "beta1pow", p, fill=opt._beta1,
                             shape=(1,))
            b2p = _get_state(opt, p.name, "beta2pow", p, fill=opt._beta2,
                             shape=(1,))
            ins.update({"Moment1": m1._array, "Moment2": m2._array,
                        "Beta1Pow": b1p._array, "Beta2Pow": b2p._array})
            op_type = {"AdamOptimizer": "adam", "Adam": "adam",
                       "AdamW": "adamw", "LambOptimizer": "lamb"}[name]
            attrs = {"beta1": opt._beta1, "beta2": opt._beta2,
                     "epsilon": opt._epsilon}
            if op_type in ("adamw", "lamb"):
                attrs["weight_decay"] = opt._weight_decay
        elif name in ("AdagradOptimizer", "Adagrad"):
            mom = _get_state(opt, p.name, "moment", p,
                             fill=opt._initial_accumulator_value)
            ins["Moment"] = mom._array
            op_type, attrs = "adagrad", {"epsilon": opt._epsilon}
        else:
            raise NotImplementedError(
                "dygraph path for %s arrives with a later wave" % name)

        info = infos.get(op_type)
        attrs = dict(attrs)
        attrs[BOUND_OUTPUTS_ATTR] = tuple(s.name for s in info.outputs)
        if tracer.lazy_engine is not None:
            outs = _lazy_opt_op(tracer.lazy_engine, info, op_type, ins,
                                attrs)
        else:
            outs = info.fn(ins, attrs)
        p._array = outs["ParamOut"]
        if "VelocityOut" in outs:
            _get_state(opt, p.name, "velocity", p)._array = outs["VelocityOut"]
        if "Moment1Out" in outs:
            _get_state(opt, p.name, "moment1", p)._array = outs["Moment1Out"]
            _get_state(opt, p.name, "moment2", p)._array = outs["Moment2Out"]
            _get_state(opt, p.name, "beta1pow", p, shape=(1,))._array = outs["Beta1PowOut"]
            _get_state(opt, p.name, "beta2pow", p, shape=(1,))._array = outs["Beta2PowOut"]
        if "MomentOut" in outs:
            _get_state(opt, p.name, "moment", p)._array = outs["MomentOut"]
    # the optimizer step is the natural flush boundary (torch/XLA's
    # mark_step): steady-state training becomes one cached dispatch
    # per step
    tracer.flush()
    return None, [(p, p._grad) for p in params]


def _lazy_opt_op(eng, info, op_type, ins, attrs):
    """Queue an optimizer op on the LazyEngine (inputs may be pending
    grads/params); returns {slot: handle}."""
    import jax

    from .lazy import aval_of as _aval

    names = [k for k in ins if ins[k] is not None]
    handles = [ins[k] for k in names]

    holder = {}

    def op_fn(vals):
        m = dict(zip(names, vals))
        outs = info.fn(m, attrs)
        slots = holder.setdefault(
            "slots", [s.name for s in info.outputs if s.name in outs])
        return tuple(outs[n] for n in slots)

    attrs_sig = repr(sorted((k, v) for k, v in attrs.items()))
    in_avals = [_aval(h) for h in handles]
    cache = eng._opt_aval_cache
    ck = (op_type, attrs_sig, tuple(names),
          tuple((tuple(a.shape), str(a.dtype)) for a in in_avals))
    hit = cache.get(ck)
    if hit is None:
        out_avals = jax.eval_shape(lambda *vs: op_fn(list(vs)), *in_avals)
        hit = (list(out_avals), list(holder["slots"]))
        cache[ck] = hit
    else:
        holder["slots"] = list(hit[1])
    sig = ("opt", op_type, attrs_sig, tuple(names))
    pend = eng.add_node(op_fn, handles, list(hit[0]), sig)
    return dict(zip(hit[1], pend))
