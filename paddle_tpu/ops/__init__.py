"""Operator corpus.

Each module registers ops into the global OpInfoMap at import. The set
mirrors the reference's ~373 registered op types
(/root/reference/paddle/fluid/operators/) in waves; each op's docstring
cites the reference file it is parity with. Kernels are pure JAX —
compiled by XLA for TPU — with Pallas used for hot fused paths (see
``fused_ops``)."""
from . import elementwise_ops  # noqa: F401
from . import activation_ops  # noqa: F401
from . import math_ops  # noqa: F401
from . import matmul_ops  # noqa: F401
from . import tensor_ops  # noqa: F401
from . import reduce_ops  # noqa: F401
from . import conv_ops  # noqa: F401
from . import norm_ops  # noqa: F401
from . import loss_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import compare_ops  # noqa: F401
from . import metrics_ops  # noqa: F401
from . import control_flow_ops  # noqa: F401
from . import collective_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import fused_ops  # noqa: F401
from . import amp_ops  # noqa: F401
from . import distributed_ops  # noqa: F401
from . import misc_ops  # noqa: F401
from . import beam_search_ops  # noqa: F401
from . import nce_ops  # noqa: F401
from . import proposal_ops  # noqa: F401
from . import quant_ops  # noqa: F401
from . import tail_ops  # noqa: F401
from . import tail_ops2  # noqa: F401
from . import gap_ops  # noqa: F401
from . import detection_tail_ops  # noqa: F401
from . import tree_ops  # noqa: F401
from . import var_conv_ops  # noqa: F401
from . import hybrid_parallel_ops  # noqa: F401
from . import ctr_ops  # noqa: F401
from . import tail_ops3  # noqa: F401
from . import text_match_ops  # noqa: F401
from . import eval_ops  # noqa: F401
