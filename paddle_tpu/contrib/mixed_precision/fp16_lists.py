"""Op lists steering mixed-precision rewriting.

Parity: /root/reference/python/paddle/fluid/contrib/mixed_precision/
fp16_lists.py:20 (AutoMixedPrecisionLists; white/black/gray sets).
TPU-first difference: the low-precision dtype is bfloat16, whose 8-bit
exponent makes the reference's fp16 overflow-driven black-listing less
critical — but the list semantics are kept so user overrides port over.
"""
from __future__ import annotations

import copy


class AutoMixedPrecisionLists:
    """Merge built-in white/black lists with user-supplied overrides."""

    def __init__(self, custom_white_list=None, custom_black_list=None):
        self._custom_white_list = custom_white_list
        self._custom_black_list = custom_black_list
        self.white_list = copy.copy(white_list)
        self.black_list = copy.copy(black_list)
        self.gray_list = copy.copy(gray_list)
        self._update_list()

    def _update_list(self):
        if self._custom_white_list and self._custom_black_list:
            for op_name in self._custom_white_list:
                if op_name in self._custom_black_list:
                    raise ValueError(
                        "Custom white list overlap custom black list: %s"
                        % op_name)
        if self._custom_white_list:
            for op_name in self._custom_white_list:
                if op_name in self.black_list:
                    self.black_list.remove(op_name)
                self.white_list.add(op_name)
        if self._custom_black_list:
            for op_name in self._custom_black_list:
                if op_name in self.white_list:
                    self.white_list.remove(op_name)
                self.black_list.add(op_name)


# MXU-bound ops: always run in bf16 (reference fp16_lists.py white_list)
white_list = {
    "conv2d",
    "conv3d",
    "conv2d_transpose",
    "matmul",
    "mul",
}

# numerically sensitive reductions/losses/normalizations: keep f32
# (reference fp16_lists.py black_list; normalization moved here from the
# reference's gray set — the TPU policy keeps stats math in f32, which
# costs nothing on bandwidth-bound elementwise ops)
black_list = {
    "exp",
    "square",
    "log",
    "mean",
    "sum",
    "cos_sim",
    "softmax",
    "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "cross_entropy",
    "cross_entropy2",
    "batch_norm",
    "layer_norm",
    "instance_norm",
    "group_norm",
}

# follow their inputs (reference gray_list)
gray_list = {
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "elementwise_mod",
    "elementwise_floordiv",
    "tanh",
    "sigmoid",
    "lookup_table",
    "top_k",
    "pool2d",
    "pool3d",
    "dropout",
    "relu",
    "relu6",
    "leaky_relu",
    "soft_relu",
    "flatten2",
    "stack",
    "unstack",
    "uniform_random_batch_size_like",
    "gaussian_random",
    "gaussian_random_batch_size_like",
    "slice",
    "rank",
    "scale",
    "transpose2",
    "reshape2",
    "gather",
    "fill_constant",
    "get_tensor_from_selected_rows",
    "sign",
    "cast",
}
