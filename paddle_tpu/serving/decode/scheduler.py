"""Per-token-step scheduler: who prefills, who decodes, who gets
evicted — decided fresh at EVERY token step.

This is the inversion that makes the engine "continuous": the one-shot
tier schedules per REQUEST (a batch forms, runs to completion, the
next batch forms), so a finished sequence's batch slot is dead weight
until the whole batch drains. Here the unit of scheduling is one token
step, and between any two steps sequences join, finish, or get evicted
— the decode batch refills immediately, which is where the
tokens-per-second win over wait-for-all batching comes from (the bench
measures exactly this).

Three decisions per step, in priority order:

- **Prefill admission, token-budgeted**: waiting sequences consume
  prompt chunks from a per-step token budget. The budget is the
  head-of-line blocking fix — a 10k-token prompt prefills across many
  steps, and the RUNNING decodes emit a token every step in between
  instead of stalling behind it (chunk boundaries are numerically free,
  see ``model.prefill_chunk``).
- **Decode batch at ladder buckets**: the active batch pads up to the
  smallest ladder bucket that fits (``batcher.pick_bucket`` — same
  discipline, same reason: a bounded set of compiled shapes on
  accelerator hosts).
- **Preemption under memory pressure**: when the KV arena can't cover
  the step, the LOWEST-priority resident sequence is evicted — blocks
  freed, generated-so-far retained — and re-admitted later as a
  re-prefill of (prompt + generated). Victims are chosen strictly
  below the requester's priority; a sequence never evicts its own
  class peers' elders (FIFO within class), and the requester defers if
  nothing outranks it.

Priority is ``(class_rank, arrival)`` — the fleet's cost classes
(interactive < batch < best_effort) then FIFO, matching the admission
ordering in ``serving/fleet.py`` so the two tiers shed the same
sequences under pressure.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

from .kvcache import PagedKVCache

__all__ = ["SeqState", "DecodeScheduler", "StepPlan"]


class SeqState:
    """One resident sequence, as the scheduler sees it. The engine
    owns the stream plumbing; this is the scheduling-relevant core."""

    __slots__ = ("seq_id", "prompt", "generated", "priority", "arrival",
                 "prefilled", "last_token", "phase", "preemptions")

    def __init__(self, seq_id: str, prompt: List[int], priority: int,
                 arrival: int):
        self.seq_id = seq_id
        self.prompt = list(prompt)
        self.generated: List[int] = []
        self.priority = int(priority)
        self.arrival = int(arrival)
        self.prefilled = 0          # tokens of replay() already in cache
        self.last_token: Optional[int] = None
        self.phase = "waiting"      # waiting | prefill | running
        self.preemptions = 0

    def replay(self) -> List[int]:
        """Tokens that must be in the cache before the next decode:
        prompt plus everything generated so far (non-empty generated
        means this is a re-prefill after preemption)."""
        return self.prompt + self.generated

    def rank(self) -> Tuple[int, int]:
        return (self.priority, self.arrival)


class StepPlan:
    """One step's work: ``prefill`` is ``[(seq, n_tokens)]`` chunks to
    run (in order), ``decode`` the sequences taking a token step,
    ``bucket`` the padded batch width for the decode call."""

    __slots__ = ("prefill", "decode", "bucket")

    def __init__(self, prefill, decode, bucket):
        self.prefill = prefill
        self.decode = decode
        self.bucket = bucket

    def empty(self) -> bool:
        return not self.prefill and not self.decode


class DecodeScheduler:
    """Owns the waiting/running sets and the per-step plan. NOT
    thread-safe by itself — the engine calls every method from its
    step thread (or under its own lock before the thread starts)."""

    def __init__(self, cache: PagedKVCache, ladder: Tuple[int, ...],
                 prefill_chunk_tokens: int = 32,
                 max_running: Optional[int] = None):
        if prefill_chunk_tokens < 1:
            raise ValueError("prefill_chunk_tokens must be >= 1")
        self.cache = cache
        self.ladder = tuple(ladder)
        self.prefill_chunk_tokens = int(prefill_chunk_tokens)
        self.max_running = int(max_running or self.ladder[-1])
        self._waiting: List[SeqState] = []   # kept rank-sorted
        self._running: List[SeqState] = []   # decode order = admit order
        self._arrival = itertools.count()

    # -- membership ---------------------------------------------------------

    def next_arrival(self) -> int:
        return next(self._arrival)

    def add(self, seq: SeqState) -> None:
        seq.phase = "waiting" if seq.prefilled < len(seq.replay()) \
            else "running"
        bucket = self._running if seq.phase == "running" else self._waiting
        bucket.append(seq)
        if bucket is self._waiting:
            self._waiting.sort(key=SeqState.rank)

    def remove(self, seq: SeqState) -> None:
        for pool in (self._waiting, self._running):
            if seq in pool:
                pool.remove(seq)

    def sequences(self) -> List[SeqState]:
        return self._waiting + self._running

    def depth(self) -> int:
        return len(self._waiting) + len(self._running)

    # -- the per-step plan --------------------------------------------------

    def plan(self) -> StepPlan:
        budget = self.prefill_chunk_tokens
        prefill: List[Tuple[SeqState, int]] = []
        # a decode slot is consumed by a running sequence OR a prefill
        # already in flight (it holds cache and will promote); new
        # sequences start prefilling only when a slot is open, so the
        # running set never outgrows the ladder
        slots = (self.max_running - len(self._running)
                 - sum(1 for s in self._waiting if s.prefilled > 0))
        for seq in self._waiting:
            if budget <= 0:
                break
            if seq.prefilled == 0:
                if slots <= 0:
                    continue
                slots -= 1
            take = min(len(seq.replay()) - seq.prefilled, budget)
            if take > 0:
                prefill.append((seq, take))
                budget -= take
        decode = self._running[:self.max_running]
        bucket = _pick(self.ladder, len(decode)) if decode else 0
        return StepPlan(prefill, decode, bucket)

    def promote(self, seq: SeqState) -> None:
        """Prefill complete: move to the decode set."""
        if seq in self._waiting:
            self._waiting.remove(seq)
        seq.phase = "running"
        if seq not in self._running:
            self._running.append(seq)

    # -- memory pressure ----------------------------------------------------

    def pick_victims(self, needed_blocks: int,
                     requester: SeqState) -> Optional[List[SeqState]]:
        """Lowest-priority resident sequences whose eviction frees at
        least ``needed_blocks``, all ranked STRICTLY below the
        requester. None if the residents below it can't cover the need
        (the requester then defers instead of evicting peers)."""
        candidates = [s for s in self._waiting + self._running
                      if s is not requester
                      and s.rank() > requester.rank()
                      and self.cache.has(s.seq_id)]
        candidates.sort(key=SeqState.rank, reverse=True)  # worst first
        victims, freed = [], 0
        bt = self.cache.config.block_tokens
        for s in candidates:
            if freed >= needed_blocks:
                break
            victims.append(s)
            freed += -(-self.cache.seq_len(s.seq_id) // bt)
        return victims if freed >= needed_blocks else None

    def preempt(self, seq: SeqState) -> int:
        """Evict: free the blocks, keep the tokens, back to waiting as
        a future re-prefill. Returns blocks freed."""
        freed = self.cache.release(seq.seq_id)
        seq.prefilled = 0
        seq.preemptions += 1
        if seq in self._running:
            self._running.remove(seq)
        if seq not in self._waiting:
            self._waiting.append(seq)
        seq.phase = "waiting"
        self._waiting.sort(key=SeqState.rank)
        return freed


def _pick(ladder, rows):
    for b in ladder:
        if b >= rows:
            return b
    return ladder[-1]
