"""Operator overloads for VarBase (eager math_op_patch).

Parity: /root/reference/python/paddle/fluid/dygraph/math_op_patch.py.
"""
from __future__ import annotations

import numpy as np

from .tracer import current_tracer
from .varbase import VarBase


def _trace(op_type, ins, attrs=None):
    return current_tracer().trace_op(op_type, ins, {}, attrs or {})


def _const(value, dtype):
    """Scalar constant as a TRACED fill_constant op — a raw VarBase
    would be invisible to program recording (@declarative trace
    replay would find an unfed variable)."""
    from ..core import dtypes as _dt

    return _trace("fill_constant", {},
                  {"shape": [1], "value": float(value),
                   "dtype": _dt.dtype_to_enum(str(dtype)),
                   "force_cpu": False})["Out"][0]


def _binary(op_type, x, y, reverse=False):
    if not isinstance(y, VarBase):
        if op_type == "elementwise_add":
            return _trace("scale", {"X": x}, {"scale": 1.0, "bias": float(y)})["Out"][0]
        if op_type == "elementwise_sub" and not reverse:
            return _trace("scale", {"X": x}, {"scale": 1.0, "bias": -float(y)})["Out"][0]
        if op_type == "elementwise_sub" and reverse:
            return _trace("scale", {"X": x}, {"scale": -1.0, "bias": float(y)})["Out"][0]
        if op_type == "elementwise_mul":
            return _trace("scale", {"X": x}, {"scale": float(y), "bias": 0.0})["Out"][0]
        if op_type == "elementwise_div" and not reverse:
            return _trace("scale", {"X": x}, {"scale": 1.0 / float(y), "bias": 0.0})["Out"][0]
        y = _const(y, np.asarray(x.numpy()).dtype)
    a, b = (y, x) if reverse else (x, y)
    return _trace(op_type, {"X": a, "Y": b}, {"axis": -1})["Out"][0]


def monkey_patch_varbase():
    def _make(op_type, reverse=False):
        def impl(self, other):
            return _binary(op_type, self, other, reverse)

        return impl

    VarBase.__add__ = _make("elementwise_add")
    VarBase.__radd__ = _make("elementwise_add")
    VarBase.__sub__ = _make("elementwise_sub")
    VarBase.__rsub__ = _make("elementwise_sub", reverse=True)
    VarBase.__mul__ = _make("elementwise_mul")
    VarBase.__rmul__ = _make("elementwise_mul")
    VarBase.__truediv__ = _make("elementwise_div")
    VarBase.__rtruediv__ = _make("elementwise_div", reverse=True)
    VarBase.__pow__ = _make("elementwise_pow")
    VarBase.__mod__ = _make("elementwise_mod")
    VarBase.__neg__ = lambda self: _trace(
        "scale", {"X": self}, {"scale": -1.0, "bias": 0.0})["Out"][0]
    VarBase.__matmul__ = lambda self, other: _trace(
        "matmul", {"X": self, "Y": other},
        {"transpose_X": False, "transpose_Y": False, "alpha": 1.0})["Out"][0]

    def _cmp(op_type):
        def impl(self, other):
            if not isinstance(other, VarBase):
                # promote: int tensor vs float threshold must compare
                # as float, not truncate the threshold into the int
                # dtype (0 > -0.5 would become 0 > 0)
                self_dt = np.asarray(self.numpy()).dtype
                dt = np.promote_types(self_dt, np.asarray(other).dtype)
                if dt.kind == "f":
                    dt = np.dtype("float32")
                other = _const(other, dt)
            return _trace(op_type, {"X": self, "Y": other})["Out"][0]

        return impl

    VarBase.__lt__ = _cmp("less_than")
    VarBase.__le__ = _cmp("less_equal")
    VarBase.__gt__ = _cmp("greater_than")
    VarBase.__ge__ = _cmp("greater_equal")
    # __eq__/__ne__ stay identity (matching static Variable + reference)

    def _bool(self):
        # eager values are concrete — numpy truthiness semantics
        arr = np.asarray(self.numpy())
        if arr.size != 1:
            raise ValueError(
                "The truth value of a multi-element VarBase is ambiguous; "
                "use .any()/.all() reductions")
        return bool(arr.reshape(-1)[0])

    VarBase.__bool__ = _bool


monkey_patch_varbase()
