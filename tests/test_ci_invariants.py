"""CI invariants wired into the suite (reference runs these as CI
scripts: tools/check_op_register_type.py, tools/print_signatures.py +
check_api_approvals.sh). ci/check.sh is the standalone entry point;
these tests make the invariants part of every `pytest tests/` run."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_op_registry_parity_is_zero():
    from paddle_tpu.tools.check_op_registry import parity_diff

    diff = parity_diff()
    if diff is None:
        pytest.skip("reference tree not mounted")
    assert diff["missing"] == [], (
        "reference ops neither registered nor allowlisted: %s"
        % diff["missing"])
    assert diff["stale_allowlist"] == [], (
        "allowlist entries now registered or gone from the reference: %s"
        % diff["stale_allowlist"])


def test_api_fingerprint_frozen():
    """The committed fingerprint must match the live surface — an API
    change requires a deliberate `ci/check.sh --update`."""
    from paddle_tpu.tools.print_signatures import DEFAULT_MODULES, iter_api

    live = []
    for m in DEFAULT_MODULES:
        live.extend(iter_api(m))
    with open(os.path.join(REPO, "ci", "api_fingerprint.txt")) as f:
        frozen = [l.rstrip("\n") for l in f if l.strip()]
    live_set, frozen_set = set(live), set(frozen)
    added = sorted(live_set - frozen_set)[:10]
    removed = sorted(frozen_set - live_set)[:10]
    assert live_set == frozen_set, (
        "public API changed; run ci/check.sh --update if intentional. "
        "added=%s removed=%s" % (added, removed))


def test_ci_check_script_exists_and_parses():
    path = os.path.join(REPO, "ci", "check.sh")
    assert os.access(path, os.X_OK)
    subprocess.run(["bash", "-n", path], check=True)
