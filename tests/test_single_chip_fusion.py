"""Single-chip fusion + async feed (ISSUE 14).

Numerics contract under test:
- the fused optimizer op (one launch over the flat param/state buffer)
  matches the per-param update chain BIT-FOR-BIT for sgd / momentum /
  adam / adamw — at the op level (same inputs, pallas-interpret AND
  XLA paths), including uneven/odd param sizes and the bf16
  master-weight (AMP) configuration;
- at the program level, a fused training run matches the unfused run
  bitwise after the first update (beyond that XLA's per-program FMA
  contraction choice bounds cross-compilation parity — the sc_smoke
  gate documents and bounds it);
- the fused epilogue ops re-emit every intermediate the pre-built
  backward reads, so fused programs train bit-identically;
- knobs default OFF, are honored by the executor, and a
  fused-optimizer program is REFUSED by the dp engine (its grads
  would dodge the allreduce transpiler);
- the async feeder double-buffers host->device staging and the
  executor passes staged jax.Arrays through without a host round-trip.
"""
import functools
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu import observability as obs
from paddle_tpu.core import fusion
from paddle_tpu.core.native_feed import AsyncDeviceFeeder
from paddle_tpu.ops.pallas.fused_optimizer import (
    LANE_PAD, fused_optimizer_update)
from paddle_tpu.ops.pallas.support import pallas_supported

KNOBS = ("PADDLE_TPU_FUSED_OPTIMIZER", "PADDLE_TPU_FUSED_EPILOGUE",
         "PADDLE_TPU_ASYNC_FEED")

SEED = 4242


@pytest.fixture(autouse=True)
def _clean_knobs(monkeypatch):
    for k in KNOBS:
        monkeypatch.delenv(k, raising=False)
    yield


def test_knobs_default_off():
    assert not fusion.fused_optimizer_enabled()
    assert not fusion.fused_epilogue_enabled()
    from paddle_tpu.core.native_feed import async_feed_enabled

    assert not async_feed_enabled()


# -- op-level parity: fused update vs per-param chain -----------------------


def _flat_inputs(op_type, sizes, dtype="float32", seed=0):
    """Per-param (p, g, states...) arrays + their flat padded concat."""
    rng = np.random.RandomState(seed)
    mk = lambda: [rng.randn(s).astype(dtype) for s in sizes]  # noqa: E731
    ps, gs = mk(), mk()
    states = {"sgd": 0, "momentum": 1, "adam": 2, "adamw": 2}[op_type]
    sts = [mk() for _ in range(states)]
    total = sum(sizes)
    padded = -(-total // LANE_PAD) * LANE_PAD

    def flat(xs):
        f = np.concatenate([x.ravel() for x in xs])
        return np.concatenate(
            [f, np.zeros(padded - total, f.dtype)]).astype(dtype)

    return ps, gs, sts, flat, total, padded


def _per_param(op_type, ps, gs, sts, lr, b1p, b2p):
    """Reference: the registered per-param optimizer fns, param by
    param (exactly what the unfused program executes)."""
    from paddle_tpu.ops import optimizer_ops as oo

    outs_p, outs_s = [], [[] for _ in sts]
    for i in range(len(ps)):
        ins = {"Param": jnp.asarray(ps[i]), "Grad": jnp.asarray(gs[i]),
               "LearningRate": jnp.asarray([lr])}
        if op_type == "momentum":
            ins["Velocity"] = jnp.asarray(sts[0][i])
            got = oo._momentum(ins, {"mu": 0.9})
            outs_s[0].append(np.asarray(got["VelocityOut"]))
        elif op_type in ("adam", "adamw"):
            ins.update({"Moment1": jnp.asarray(sts[0][i]),
                        "Moment2": jnp.asarray(sts[1][i]),
                        "Beta1Pow": jnp.asarray([b1p]),
                        "Beta2Pow": jnp.asarray([b2p])})
            fn = oo._adam if op_type == "adam" else oo._adamw
            got = fn(ins, {"beta1": 0.9, "beta2": 0.999,
                           "epsilon": 1e-8, "weight_decay": 0.01})
            outs_s[0].append(np.asarray(got["Moment1Out"]))
            outs_s[1].append(np.asarray(got["Moment2Out"]))
        else:
            got = oo._sgd(ins, {})
        outs_p.append(np.asarray(got["ParamOut"]))
    return outs_p, outs_s


@pytest.mark.parametrize("op_type", ["sgd", "momentum", "adam", "adamw"])
def test_fused_update_matches_per_param(op_type):
    """Fused flat update (XLA fallback path) vs the per-param kernels,
    bit-for-bit — including odd/uneven param sizes straddling the pad
    boundary."""
    sizes = [7, 129, 1024, 33]   # uneven, odd, lane-aligned, tiny
    ps, gs, sts, flat, total, padded = _flat_inputs(op_type, sizes)
    lr, b1p, b2p = np.float32(0.01), np.float32(0.9), np.float32(0.999)
    attrs = {"mu": 0.9, "beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
             "weight_decay": 0.01}

    p_out, sa, sb = fused_optimizer_update(
        op_type, attrs, jnp.asarray(flat(ps)), jnp.asarray(flat(gs)),
        jnp.asarray(lr),
        jnp.asarray(flat(sts[0])) if sts else None,
        jnp.asarray(flat(sts[1])) if len(sts) > 1 else None,
        jnp.asarray([b1p]), jnp.asarray([b2p]),
        force_pallas=False)
    ref_p, ref_s = _per_param(op_type, ps, gs, sts, lr, b1p, b2p)

    off = 0
    for i, s in enumerate(sizes):
        np.testing.assert_array_equal(
            np.asarray(p_out)[off:off + s], ref_p[i],
            err_msg="param %d (%s)" % (i, op_type))
        if sts:
            np.testing.assert_array_equal(
                np.asarray(sa)[off:off + s], ref_s[0][i])
        if len(sts) > 1:
            np.testing.assert_array_equal(
                np.asarray(sb)[off:off + s], ref_s[1][i])
        off += s
    # zero padding stays inert state-wise (no NaN from the pad region)
    assert np.all(np.isfinite(np.asarray(p_out)[total:]))


@pytest.mark.parametrize("op_type", ["sgd", "momentum", "adam", "adamw"])
def test_pallas_kernel_matches_xla_path(op_type):
    """The pallas streaming kernel (interpret mode on CPU) is
    bit-identical to the XLA fallback on the same flat buffers — the
    two lowerings of the one update definition."""
    if not pallas_supported(interpret=True):
        pytest.skip("pallas interpret mode unavailable")
    sizes = [512, 321, 190]
    ps, gs, sts, flat, total, padded = _flat_inputs(op_type, sizes,
                                                    seed=3)
    lr, b1p, b2p = np.float32(0.05), np.float32(0.81), np.float32(0.99)
    attrs = {"mu": 0.9, "beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
             "weight_decay": 0.01}
    args = (jnp.asarray(flat(ps)), jnp.asarray(flat(gs)),
            jnp.asarray(lr),
            jnp.asarray(flat(sts[0])) if sts else None,
            jnp.asarray(flat(sts[1])) if len(sts) > 1 else None,
            jnp.asarray([b1p]), jnp.asarray([b2p]))
    got_pl = fused_optimizer_update(op_type, attrs, *args,
                                    force_pallas=True)
    # jit the fallback: in a real program the op body runs inside the
    # whole-program jit, and only the JITTED lowering shares the pallas
    # kernel's FMA contraction (eager dispatch evaluates mul-then-sub
    # uncontracted — 1 ULP apart on ~5% of elements)
    got_xla = jax.jit(functools.partial(
        fused_optimizer_update, op_type, attrs,
        force_pallas=False))(*args)
    for a, b in zip(got_pl, got_xla):
        if a is None or b is None:
            assert a is None and b is None
            continue
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- program-level parity ---------------------------------------------------


def _build_mlp(optimizer="adam", sizes=(33, 17), amp=False):
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = SEED
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[8, 16], dtype="float32")
        lbl = fluid.data(name="lbl", shape=[8, 1], dtype="int64")
        h = x
        for s in sizes:
            h = fluid.layers.fc(h, size=s, act="gelu")
        pred = fluid.layers.fc(h, size=10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, lbl))
        if optimizer == "sgd":
            opt = fluid.optimizer.SGD(0.1)
        elif optimizer == "momentum":
            opt = fluid.optimizer.MomentumOptimizer(0.1, 0.9)
        elif optimizer == "adamw":
            opt = fluid.optimizer.AdamW(1e-3)
        else:
            opt = fluid.optimizer.AdamOptimizer(1e-3)
        if amp:
            from paddle_tpu.contrib import mixed_precision as mp

            opt = mp.decorate(opt)
        opt.minimize(loss)
    rng = np.random.RandomState(7)
    feed = {"x": rng.rand(8, 16).astype("float32"),
            "lbl": rng.randint(0, 10, (8, 1)).astype("int64")}
    return main, startup, loss, feed


def _train(build_kwargs, knobs, steps=3):
    for k in KNOBS:
        os.environ.pop(k, None)
    os.environ.update(knobs)
    try:
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            main, startup, loss, feed = _build_mlp(**build_kwargs)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            params1 = None
            losses = []
            for i in range(steps):
                if i == 1:
                    params1 = _persistables(main, scope)
                losses.append(float(exe.run(main, feed=feed,
                                            fetch_list=[loss])[0]))
            return {"ops": [op.type for op in main.global_block().ops],
                    "losses": losses, "params1": params1,
                    "params": _persistables(main, scope),
                    "main": main, "scope": scope, "exe": exe,
                    "startup": startup, "feed": feed, "loss": loss}
    finally:
        for k in KNOBS:
            os.environ.pop(k, None)


def _persistables(main, scope):
    got = {}
    for v in main.global_block().vars.values():
        if not v.persistable:
            continue
        var = scope.find_var(v.name)
        if var is not None and var.is_initialized():
            got[v.name] = np.asarray(var.raw().array)
    return got


def _assert_step1_bitwise(base, fused):
    common = [k for k in base["params1"] if k in fused["params1"]]
    assert common
    for k in common:
        np.testing.assert_array_equal(base["params1"][k],
                                      fused["params1"][k],
                                      err_msg="step-1 param %r" % k)


@pytest.mark.parametrize("layout", ["1", "chain", "flat"])
@pytest.mark.parametrize("optimizer", ["sgd", "momentum", "adam",
                                       "adamw"])
def test_program_fused_optimizer_parity(optimizer, layout):
    """Both layouts of the fused op ("1" = auto = chain on this CPU
    backend; "flat" is the pallas/TPU layout run through its XLA
    lowering here) match the per-param program."""
    base = _train({"optimizer": optimizer}, {})
    fused = _train({"optimizer": optimizer},
                   {"PADDLE_TPU_FUSED_OPTIMIZER": layout})
    assert "fused_optimizer" in fused["ops"], fused["ops"]
    assert optimizer not in fused["ops"]
    assert len(fused["ops"]) < len(base["ops"])
    _assert_step1_bitwise(base, fused)
    for lb, lf in zip(base["losses"], fused["losses"]):
        assert abs(lb - lf) <= 1e-4 * max(abs(lb), 1e-6)
    fop = next(op for op in fused["main"].global_block().ops
               if op.type == "fused_optimizer")
    want = "flat" if layout == "flat" else "chain"
    assert fop.attrs["layout"] == want
    if want == "chain":
        # chain layout keeps the per-param accumulators in place —
        # no flat re-layout, nothing registered for restart resync
        assert not getattr(fused["main"], "_sharded_flat_layout", None)


def test_program_fused_epilogue_parity():
    base = _train({}, {})
    fused = _train({}, {"PADDLE_TPU_FUSED_EPILOGUE": "1"})
    assert "fused_bias_act" in fused["ops"], fused["ops"]
    assert len(fused["ops"]) < len(base["ops"])
    # epilogue fusion composes the SAME registered kernels — the whole
    # run stays bitwise, not just step 1
    _assert_step1_bitwise(base, fused)
    for k in base["params"]:
        if k in fused["params"]:
            np.testing.assert_array_equal(base["params"][k],
                                          fused["params"][k])
    assert base["losses"] == fused["losses"]


def test_program_both_passes_parity():
    base = _train({}, {})
    both = _train({}, {"PADDLE_TPU_FUSED_OPTIMIZER": "1",
                       "PADDLE_TPU_FUSED_EPILOGUE": "1"})
    assert "fused_optimizer" in both["ops"]
    assert "fused_bias_act" in both["ops"]
    _assert_step1_bitwise(base, both)


def test_bf16_master_weight_path():
    """AMP-decorated training (bf16 compute, f32 master weights): the
    fused pass must still group the f32 master updates and match the
    per-param path on the first step."""
    base = _train({"optimizer": "adam", "amp": True}, {})
    fused = _train({"optimizer": "adam", "amp": True},
                   {"PADDLE_TPU_FUSED_OPTIMIZER": "1"})
    assert "fused_optimizer" in fused["ops"], \
        "AMP master-weight updates did not fuse: %s" % fused["ops"]
    _assert_step1_bitwise(base, fused)


def test_single_member_groups_stay_per_param():
    """One param per optimizer instance = nothing to fuse — the pass
    must leave the program alone rather than churn state layout."""
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = SEED
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[4, 4], dtype="float32")
        y = fluid.layers.fc(x, size=2, bias_attr=False)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        n = fusion.apply_fused_optimizer(main, scope)
    assert n == 0
    assert "fused_optimizer" not in [op.type
                                     for op in main.global_block().ops]


def test_restart_resync_rebuilds_flat_state():
    """Re-running the startup program after FLAT-layout fusion must
    rebuild the flat optimizer state from the re-initialized
    per-param vars — the same restart contract the sharded update
    keeps. (The chain layout keeps per-param state vars, which the
    startup re-run re-initializes directly — nothing to resync.)"""
    r = _train({"optimizer": "momentum"},
               {"PADDLE_TPU_FUSED_OPTIMIZER": "flat"}, steps=3)
    main, scope, exe = r["main"], r["scope"], r["exe"]
    flat_names = [n for n in getattr(main, "_sharded_flat_layout", {})]
    assert flat_names
    with fluid.scope_guard(scope):
        trained = np.asarray(scope.find_var(
            flat_names[0]).raw().array).copy()
        assert np.any(trained != 0.0)  # momentum accumulated
        os.environ["PADDLE_TPU_FUSED_OPTIMIZER"] = "1"
        try:
            exe.run(r["startup"])   # restart: re-inits per-param vars
            exe.run(main, feed=r["feed"], fetch_list=[r["loss"]])
        finally:
            os.environ.pop("PADDLE_TPU_FUSED_OPTIMIZER", None)
        after = np.asarray(scope.find_var(flat_names[0]).raw().array)
    # after ONE fresh step, velocity == grad (mu*0 + g), not the old
    # trained accumulator — the resync caught the restart
    assert not np.array_equal(trained, after)


def test_dp_engine_refuses_fused_program():
    from paddle_tpu.parallel.mesh_utils import make_mesh

    r = _train({"optimizer": "adam"},
               {"PADDLE_TPU_FUSED_OPTIMIZER": "1"}, steps=1)
    main = r["main"]
    assert getattr(main, "_fused_optimizer_groups", 0) >= 1
    with fluid.scope_guard(r["scope"]):
        cp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=r["loss"].name, places=make_mesh([2], ["dp"]))
        with pytest.raises(ValueError, match="fused-optimizer"):
            r["exe"].run(cp, feed=r["feed"], fetch_list=[r["loss"]])


def test_dp_transpiled_program_declines_fusion():
    from paddle_tpu.parallel.transpiler import insert_allreduce_ops

    main, startup, loss, feed = _build_mlp()
    insert_allreduce_ops(main, 4)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        n = fusion.apply_fused_optimizer(main, scope)
    assert n == 0


# -- fused epilogue op semantics -------------------------------------------


def test_epilogue_dropout_stream_parity():
    """add -> gelu -> dropout fuses with the ORIGINAL dropout op's RNG
    stream (the carried _fwd_op_id), so masks — and training — match
    the unfused program bit-for-bit."""
    def build():
        main, startup = fluid.Program(), fluid.Program()
        startup.random_seed = SEED
        with fluid.unique_name.guard(), fluid.program_guard(main,
                                                            startup):
            x = fluid.data(name="x", shape=[8, 16], dtype="float32")
            lbl = fluid.data(name="lbl", shape=[8, 1], dtype="int64")
            h = fluid.layers.fc(x, size=32, act="gelu")
            h = fluid.layers.dropout(h, dropout_prob=0.3)
            pred = fluid.layers.fc(h, size=10, act="softmax")
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, lbl))
            fluid.optimizer.SGD(0.1).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(7)
    feed = {"x": rng.rand(8, 16).astype("float32"),
            "lbl": rng.randint(0, 10, (8, 1)).astype("int64")}

    def run(knob):
        for k in KNOBS:
            os.environ.pop(k, None)
        if knob:
            os.environ["PADDLE_TPU_FUSED_EPILOGUE"] = "1"
        try:
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                main, startup, loss = build()
                exe = fluid.Executor(fluid.CPUPlace())
                # pin the RNG stream base so both runs draw the same
                # per-op dropout seeds
                exe._core.rng.seed = 99991
                exe._core.rng.step = 0
                exe.run(startup)
                losses = [float(exe.run(main, feed=feed,
                                        fetch_list=[loss])[0])
                          for _ in range(3)]
                return losses, [op.type
                                for op in main.global_block().ops], \
                    _persistables(main, scope)
        finally:
            os.environ.pop("PADDLE_TPU_FUSED_EPILOGUE", None)

    l0, ops0, p0 = run(False)
    l1, ops1, p1 = run(True)
    assert "dropout" in ops0
    assert "fused_bias_act" in ops1 and "dropout" not in ops1, ops1
    assert l0 == l1, (l0, l1)
    for k in p0:
        if k in p1:
            np.testing.assert_array_equal(p0[k], p1[k])


def test_epilogue_fusion_keeps_forward_phase_classification():
    """The fused dropout chain carries _rng_op_id, NOT _fwd_op_id —
    the latter marks BACKWARD ops for classify_ops, and stamping it
    on a forward fused op would flip the rest of the forward region
    (and every phase metric built on it) to 'backward'."""
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = SEED
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[8, 16], dtype="float32")
        lbl = fluid.data(name="lbl", shape=[8, 1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="gelu")
        h = fluid.layers.dropout(h, dropout_prob=0.3)
        h = fluid.layers.fc(h, size=32, act="relu")
        pred = fluid.layers.fc(h, size=10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, lbl))
        fluid.optimizer.SGD(0.1).minimize(loss)
    n = fusion.apply_fused_epilogues(main)
    assert n >= 2
    from paddle_tpu.observability.profiler import classify_ops

    block = main.global_block()
    phases = classify_ops(block)
    fused_idx = [i for i, op in enumerate(block.ops)
                 if op.type == "fused_bias_act"]
    dropout_fused = [i for i in fused_idx
                     if block.ops[i].attrs.get("dropout_prob",
                                               -1.0) >= 0]
    assert dropout_fused, "dropout chain did not fuse"
    for i in fused_idx:
        assert phases[i] == "forward", (i, phases)
        assert "_fwd_op_id" not in block.ops[i].attrs
    # ops after the fused dropout but before backward stay forward
    first_bwd = phases.index("backward")
    assert first_bwd > max(fused_idx)


def test_epilogue_preserves_read_intermediates():
    """The fused op re-emits the add intermediate under its original
    name — a fetch of that name still works after fusion."""
    r = _train({}, {"PADDLE_TPU_FUSED_EPILOGUE": "1"}, steps=1)
    main = r["main"]
    fop = next(op for op in main.global_block().ops
               if op.type == "fused_bias_act")
    inter = fop.output("AddOut")[0]
    with fluid.scope_guard(r["scope"]):
        out = r["exe"].run(main, feed=r["feed"],
                           fetch_list=[r["loss"], inter])
    assert np.asarray(out[1]).shape[0] == 8


# -- async feed -------------------------------------------------------------


def test_async_feeder_yields_staged_batches():
    rng = np.random.RandomState(0)
    batches = [{"x": rng.rand(4, 4).astype("f4"),
                "y": np.int64([i])} for i in range(5)]
    got = []
    with AsyncDeviceFeeder(iter(batches), depth=2) as fdr:
        for b in fdr:
            assert isinstance(b["x"], jax.Array)
            got.append(int(np.asarray(b["y"])[0]))
    assert got == [0, 1, 2, 3, 4]


def test_async_feeder_propagates_errors():
    def gen():
        yield {"x": np.zeros((2, 2), "f4")}
        raise RuntimeError("reader exploded")

    fdr = AsyncDeviceFeeder(gen())
    next(fdr)
    with pytest.raises(RuntimeError, match="reader exploded"):
        next(fdr)
    fdr.close()


def test_async_feeder_close_mid_stream():
    fdr = AsyncDeviceFeeder(({"x": np.zeros((2, 2), "f4")}
                             for _ in range(100)), depth=2)
    next(fdr)
    fdr.close()   # must not hang on the full queue
    assert not fdr._thread.is_alive()


def test_async_feeder_close_depth1_no_deadlock():
    """depth=1 shutdown race: an in-flight put can refill the single
    slot right after close() drains it — the pump's bounded put must
    re-check the close flag instead of blocking forever."""
    import time as _t

    for _ in range(3):
        fdr = AsyncDeviceFeeder(({"x": np.zeros((2, 2), "f4")}
                                 for _ in range(100)), depth=1)
        next(fdr)
        t0 = _t.perf_counter()
        fdr.close()
        assert _t.perf_counter() - t0 < 2.0, "close() stalled"
        assert not fdr._thread.is_alive(), "pump thread leaked"


def test_executor_accepts_device_array_feeds():
    """jax.Array feed values (what the feeder yields) run through the
    compiled path and match numpy feeds exactly."""
    main, startup, loss, feed = _build_mlp(optimizer="sgd")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        l_np = float(exe.run(main, feed=feed, fetch_list=[loss])[0])
        dev_feed = {k: jax.device_put(v) for k, v in feed.items()}
        l_dev = float(exe.run(main, feed=dev_feed,
                              fetch_list=[loss])[0])
    # same feed values, one staged ahead of time — and the forward of
    # step 2 differs from step 1 only via the sgd update, so just pin
    # finiteness + that the device-fed step ran the compiled path
    assert np.isfinite(l_np) and np.isfinite(l_dev)


def test_bench_time_steps_async_feed_loop():
    """bench.py's timed loop under PADDLE_TPU_ASYNC_FEED must produce
    the same losses as the device-staged default (same batch either
    way) and record the feed fields in the diag."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    main, startup, loss, feed = _build_mlp(optimizer="sgd")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        os.environ["PADDLE_TPU_ASYNC_FEED"] = "1"
        try:
            dt, final_loss, diag = bench._time_steps(
                exe, main, feed, loss, warmup=1, iters=3, windows=1)
        finally:
            os.environ.pop("PADDLE_TPU_ASYNC_FEED", None)
    assert np.isfinite(final_loss)
    assert diag["async_feed"] is True
    assert diag["feed_ms"] is not None
    assert diag["feed_ms_sync"] is not None
    assert diag["whole_compile"], diag


# -- profiler integration ---------------------------------------------------


def test_profile_step_reports_feed_and_optimizer_ms():
    r = _train({"optimizer": "adam"},
               {"PADDLE_TPU_FUSED_OPTIMIZER": "1"}, steps=2)
    from paddle_tpu.observability import profiler as prof

    with fluid.scope_guard(r["scope"]):
        rep = prof.profile_step(r["main"], r["scope"], r["feed"])
    assert rep["feed_ms"] >= 0.0
    assert rep["optimizer_ms"] >= 0.0
    assert rep["optimizer_ms"] == rep["phase_ms"].get("optimizer", 0.0)
    # the fused op classifies as optimizer phase
    from paddle_tpu.observability.profiler import classify_ops

    phases = classify_ops(r["main"].global_block())
    ops = [op.type for op in r["main"].global_block().ops]
    assert phases[ops.index("fused_optimizer")] == "optimizer"


def test_fused_ops_have_flop_entries():
    """Fusing must not zero out the analytic FLOP account (mfu_est
    would silently drop)."""
    base = _train({}, {}, steps=1)
    both = _train({}, {"PADDLE_TPU_FUSED_OPTIMIZER": "1",
                       "PADDLE_TPU_FUSED_EPILOGUE": "1"}, steps=1)
    from paddle_tpu.observability import profiler as prof

    f_base = prof.program_flops(base["main"])
    f_both = prof.program_flops(both["main"])
    assert f_both["by_category"].get("optimizer", 0) > 0
    # fused total stays within 2% of the unfused account (the
    # epilogue estimators are coarse but must not vanish)
    assert abs(f_both["total"] - f_base["total"]) \
        <= 0.02 * f_base["total"]


# -- lazy dygraph flush-overhead satellite ----------------------------------


def test_lazy_recompiles_stay_flat():
    """Steady-state lazy training: after warmup, further steps add
    ZERO lazy.recompiles (the structure signature — including cached
    ndarray attr digests — is stable across flushes)."""
    obs.enable()
    from paddle_tpu.dygraph import Linear, to_variable

    with fluid.dygraph.guard(lazy=True):
        l1 = Linear(16, 32, act="relu")
        l2 = Linear(32, 10)
        params = l1.parameters() + l2.parameters()
        opt = fluid.optimizer.AdamOptimizer(1e-3, parameter_list=params)
        rng = np.random.RandomState(0)
        x = rng.rand(8, 16).astype("float32")
        y = rng.randint(0, 10, (8, 1)).astype("int64")

        def step():
            logits = l2(l1(to_variable(x)))
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(
                    logits, to_variable(y)))
            loss.backward()
            opt.minimize(loss, parameter_list=params)
            for p in params:
                p.clear_gradient()
            return loss

        for _ in range(3):
            loss = step()
        float(np.asarray(loss.numpy()).ravel()[0])
        before = obs.counter_value("lazy.recompiles") or 0
        for _ in range(3):
            loss = step()
        float(np.asarray(loss.numpy()).ravel()[0])
        after = obs.counter_value("lazy.recompiles") or 0
    assert after == before, (
        "lazy steady state recompiled %d times" % (after - before))


def test_ndarray_attr_digest_cached():
    from paddle_tpu.dygraph import tracer as tr

    arr = np.arange(64, dtype="f4").reshape(8, 8)
    d1 = tr._canon_attr(arr)
    assert id(arr) in tr._ndarray_digests
    import hashlib

    calls = []
    real = hashlib.sha1

    def counting(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    hashlib.sha1 = counting
    try:
        d2 = tr._canon_attr(arr)
    finally:
        hashlib.sha1 = real
    assert d1 == d2
    assert not calls, "cached ndarray attr was re-hashed"
    # a DIFFERENT array with identical content still hashes by content
    arr2 = arr.copy()
    assert tr._canon_attr(arr2) == d1
