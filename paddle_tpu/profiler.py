"""Profiler.

Parity: /root/reference/python/paddle/fluid/profiler.py (:253 profiler
context manager, :129 start_profiler, :196 stop_profiler) + the C++
RecordEvent/DeviceTracer pair (platform/profiler.cc, device_tracer.cc).

TPU-native: host-side op events are timed in the executors; device-side
tracing delegates to jax.profiler (XPlane -> TensorBoard / Perfetto),
which replaces the CUPTI DeviceTracer + chrome-trace toolchain
(tools/timeline.py). `profiler(...)` writes an XPlane trace dir and
prints a per-op host summary table.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

__all__ = ["cuda_profiler", "reset_profiler", "profiler", "start_profiler",
           "stop_profiler"]

_host_events = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]
_trace_events = []  # (name, t0_us, dur_us) — chrome-trace export
_last_trace = []  # snapshot of the finished session (stop clears live)
_enabled = False
_trace_dir = None


class RecordEvent:
    """RAII op-phase annotation (reference platform/profiler.cc:66)."""

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if _enabled:
            dur = time.perf_counter() - self._t0
            ev = _host_events[self.name]
            ev[0] += 1
            ev[1] += dur
            _trace_events.append(
                (self.name, self._t0 * 1e6, dur * 1e6))
        return False


def record_event(name):
    return RecordEvent(name)


def is_profiler_enabled():
    return _enabled


def get_trace_events():
    """(name, ts_us, dur_us) host events for timeline export: the live
    session while profiling, else the last finished session's snapshot
    (stop_profiler clears live state so sessions never bleed)."""
    return list(_trace_events) if _enabled else list(_last_trace)


def reset_profiler():
    _host_events.clear()
    del _trace_events[:]


def start_profiler(state="All", tracer_option=None, trace_dir=None):
    global _enabled, _trace_dir
    _enabled = True
    _trace_dir = trace_dir
    if trace_dir:
        import jax

        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key="total", profile_path="/tmp/profile"):
    global _enabled
    _enabled = False
    if _trace_dir:
        import jax

        jax.profiler.stop_trace()
    rows = sorted(_host_events.items(), key=lambda kv: -kv[1][1])
    if rows:
        print("%-40s %10s %14s %14s" % ("Event", "Calls", "Total(ms)", "Avg(ms)"))
        for name, (count, total) in rows[:50]:
            print("%-40s %10d %14.3f %14.3f"
                  % (name, count, total * 1e3, total * 1e3 / max(count, 1)))
    # snapshot-and-clear so back-to-back sessions never bleed into each
    # other (the reference's DisableProfiler resets after emitting)
    del _last_trace[:]
    _last_trace.extend(_trace_events)
    del _trace_events[:]
    _host_events.clear()


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path="/tmp/profile",
             tracer_option=None):
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    # name kept for API compatibility; delegates to the XLA trace
    with profiler():
        yield
