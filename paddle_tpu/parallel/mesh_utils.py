"""Mesh construction helpers."""
from __future__ import annotations

from typing import Optional, Sequence, Tuple


def make_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str],
              devices=None):
    import jax
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    n = int(np.prod(axis_sizes))
    if n > len(devices):
        raise ValueError("mesh wants %d devices, only %d available"
                         % (n, len(devices)))
    arr = np.array(devices[:n]).reshape(tuple(axis_sizes))
    return jax.sharding.Mesh(arr, tuple(axis_names))


def default_mesh(num_devices: Optional[int] = None, axis_name: str = "dp"):
    import jax

    devs = jax.devices()
    n = num_devices or len(devs)
    return make_mesh([n], [axis_name], devs)


def mesh_key(mesh) -> Tuple:
    """Stable mesh identity for executable-cache keys: id(mesh) can be
    reused by a new mesh after GC and alias a stale executable compiled
    for different devices."""
    return (tuple(d.id for d in mesh.devices.flat),
            tuple(mesh.axis_names))


def shard_map_compat(f, mesh, in_specs, out_specs):
    """shard_map across JAX versions: new jax.shard_map(check_vma=...)
    with fallback to jax.experimental.shard_map(check_rep=...)."""
    import jax

    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map

        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)
