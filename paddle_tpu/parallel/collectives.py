"""Fast collective path: bucketed / quantized allreduce + cross-replica
sharded weight update (program rewrites over the transpiled IR).

Two PAPERS.md blueprints, applied as passes after
``transpiler.insert_allreduce_ops``:

- **Bucketed gradient allreduce** (``bucket_allreduce_ops``): N per-grad
  ``c_allreduce_sum`` ops coalesce into few ``c_bucket_allreduce`` ops
  (one flat psum each). Buckets are assembled in grad *availability*
  order — the order backward produces them — and each bucket op is
  hoisted to just after the last op that touches any of its grads, so
  early buckets reduce while later backward compute still runs (XLA
  overlaps the independent collective), and a size cap
  (``PADDLE_TPU_BUCKET_MB``) keeps buckets pipelined instead of one
  giant end-of-step psum. Bit-for-bit: psum is elementwise over
  replicas, so concat-then-psum == psum-then-concat.

- **Quantized allreduce** (EQuARX): opt-in via
  ``PADDLE_TPU_QUANT_ALLREDUCE=bf16|int8`` — the bucket payload crosses
  the wire compressed (per-bucket scale for int8; see
  ``ops.collective_ops.quantized_psum``). Off by default; gated by the
  measured-error + mlp-convergence tests in tests/test_collectives.py.

- **Cross-replica sharded weight update**
  (``apply_sharded_weight_update``): each optimizer instance's per-param
  (allreduce, update) pairs collapse into ONE ``c_sharded_update`` op —
  one flat grad psum, each replica updates its 1/n shard of the flat
  param/optimizer state, one allgather of updated param shards.
  Optimizer state lives in flat vars sharded over the data axis (a
  shard spec the engine's shard_map honors), so each replica holds 1/n
  of the moments — the paper's memory/compute win. Opt-in via
  ``PADDLE_TPU_SHARDED_UPDATE=1`` or
  ``BuildStrategy.fuse_all_optimizer_ops``.

- **Profile-guided bucket planning** (``plan_buckets_profile``,
  ``PADDLE_TPU_BUCKET_PLAN=profile``): bucket boundaries chosen from a
  saved step-profile report (``PADDLE_TPU_BUCKET_PROFILE`` names the
  json — a bench record, its ``profile`` block, or a raw
  ``profiler.profile_step`` dict) instead of the byte cap: a cost
  model fitted to the measured per-bucket costs prices every candidate
  bucket against the measured backward compute remaining after its
  availability point, so buckets close exactly where the measurement
  says further coalescing would expose wire time (DynaFlow-style
  scheduling from measured operator timing, PAPERS.md). Bit-for-bit
  like any bucketing; a missing/stale report falls back to the size
  plan (``parallel.bucket_plan{mode=}`` records which ran).

Knob summary (read once per program, at first mesh run):

==============================  ============================================
``PADDLE_TPU_BUCKET_MB``        bucket cap in MB (default 4; ``0`` disables
                                bucketing). ``BuildStrategy.
                                fuse_all_reduce_ops=False`` also disables.
``PADDLE_TPU_QUANT_ALLREDUCE``  ``bf16`` | ``int8`` (default off/exact)
``PADDLE_TPU_SHARDED_UPDATE``   ``1`` enables, ``0`` forces off (overrides
                                the BuildStrategy knob either way)
``PADDLE_TPU_BUCKET_PLAN``      ``size`` (default) | ``profile``
``PADDLE_TPU_BUCKET_PROFILE``   path to the saved profile report the
                                ``profile`` plan consumes
==============================  ============================================
"""
from __future__ import annotations

import hashlib
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis.contracts import checked_rewrite
from ..ops.collective_ops import QUANT_WIRE_ITEMSIZE, SHARDED_UPDATE_SLOTS
from .transpiler import _bump_version, _merge_data_axes

DEFAULT_BUCKET_MB = 4.0

# profile-guided planner: stay safely under the measured hide budget —
# a bucket predicted to cost more than this fraction of the backward
# compute remaining after its anchor is closed early instead
PROFILE_PLAN_BUDGET_FRAC = 0.5

# optimizer ops whose update math is elementwise in (param, grad, state)
# — the precondition for flat-shard updates being bit-for-bit with the
# per-param path. lars/lamb (param-norm terms) and friends stay on the
# per-param path. SHARDED_UPDATE_SLOTS also names each op's accumulator
# input slots, folded into the flat sharded state vars.
_SHARDABLE_OPTIMIZERS = frozenset(SHARDED_UPDATE_SLOTS)


def bucket_mb(build_strategy=None) -> float:
    if build_strategy is not None and not getattr(
            build_strategy, "fuse_all_reduce_ops", True):
        return 0.0
    raw = os.environ.get("PADDLE_TPU_BUCKET_MB", "").strip()
    if not raw:
        return DEFAULT_BUCKET_MB
    try:
        return max(0.0, float(raw))
    except ValueError:
        return DEFAULT_BUCKET_MB


def quant_mode() -> str:
    raw = os.environ.get("PADDLE_TPU_QUANT_ALLREDUCE", "").strip().lower()
    if raw in ("", "0", "none", "off", "false"):
        return "none"
    if raw not in QUANT_WIRE_ITEMSIZE:
        raise ValueError(
            "PADDLE_TPU_QUANT_ALLREDUCE=%r (want bf16 or int8)" % raw)
    return raw


def bucket_plan_mode() -> str:
    """``PADDLE_TPU_BUCKET_PLAN``: ``size`` (default — the static
    byte-cap greedy plan) or ``profile`` (measurement-driven: bucket
    boundaries chosen against a saved ``profile_step`` report named by
    ``PADDLE_TPU_BUCKET_PROFILE``)."""
    raw = os.environ.get("PADDLE_TPU_BUCKET_PLAN", "").strip().lower()
    if raw in ("", "size", "static"):
        return "size"
    if raw == "profile":
        return "profile"
    raise ValueError(
        "PADDLE_TPU_BUCKET_PLAN=%r (want size or profile)" % raw)


def load_profile_report(path: Optional[str] = None) -> Optional[Dict]:
    """The saved step-profile report a profile-guided plan consumes:
    a ``profiler.profile_step`` dict (or a bench record / ``profile``
    block wrapping one) with ``per_bucket`` (measured per-bucket cost
    vs bytes) and ``backward_segments`` (measured backward time per
    compute-position range). None when the path is unset/unreadable or
    the document lacks the required fields — callers fall back to the
    size plan, never crash the step. (Thin wrapper over the shared
    ``observability.steering.load_report`` loader every report
    consumer now goes through.)"""
    from ..observability import steering

    return steering.load_report(path)


def sharded_update_enabled(build_strategy=None) -> bool:
    raw = os.environ.get("PADDLE_TPU_SHARDED_UPDATE", "").strip()
    if raw:
        return raw.lower() in ("1", "true", "yes", "on")
    return bool(build_strategy is not None and getattr(
        build_strategy, "fuse_all_optimizer_ops", False))


def _lookup_value(store, name):
    """Live value of ``name`` from either a Scope or a plain state
    mapping (engine's scope-state dict); None when absent."""
    if store is None or not name:
        return None
    find = getattr(store, "find_var", None)
    if find is None:
        return store.get(name)
    var = find(name)
    if var is not None and var.is_initialized():
        return var.raw().array
    return None


def _numel_and_dtype(block, store, name) -> Tuple[Optional[int], str]:
    """Element count + dtype of a var, best effort: block var shape,
    else its live value (Scope or state mapping), else the replicated
    param a grad mirrors. The ONE size resolver behind both the bucket
    planner's byte accounting and engine._var_nbytes — the two must
    agree for the bucketing/quantization counters to be coherent."""
    from ..core.lod_lowering import _grad_base

    v = block._find_var_recursive(name)
    shape = getattr(v, "shape", None) if v is not None else None
    dtype = str(getattr(v, "dtype", None) or "float32")
    if shape and all(isinstance(s, int) and s > 0 for s in shape):
        return int(np.prod(shape)), dtype
    arr = _lookup_value(store, name)
    if arr is not None:
        return int(getattr(arr, "size", 0)), str(arr.dtype)
    base = _grad_base(name)
    if base:
        bv = block._find_var_recursive(base)
        bshape = getattr(bv, "shape", None) if bv is not None else None
        if bshape and all(isinstance(s, int) and s > 0 for s in bshape):
            return (int(np.prod(bshape)),
                    str(getattr(bv, "dtype", None) or "float32"))
        arr = _lookup_value(store, base)
        if arr is not None:
            return int(getattr(arr, "size", 0)), str(arr.dtype)
    return None, dtype


def maybe_rewrite_collectives(program, scope, nranks: int, data_axes,
                              build_strategy=None, multiproc=False) -> None:
    """Engine entry point: apply the sharded-update pass (opt-in), then
    bucket whatever per-grad allreduces remain, then the placement-era
    schedule shaping (reduction-strategy spelling, per-bucket quant +
    error feedback, async start/await — parallel/scheduling.py). All
    passes are idempotent per program (same contract as
    insert_allreduce_ops); the knobs are read at the program's FIRST
    mesh run and baked in. With ``PADDLE_TPU_PLACEMENT_PLAN`` set, a
    searched placement artifact (paddle_tpu/placement) OVERRIDES the
    hand knobs wholesale — the plan names the same decisions the env
    vars do, chosen by the verifier-gated search instead of an
    operator."""
    if nranks <= 1 or not data_axes:
        return
    from ..placement.plan import active_plan

    pplan = active_plan()
    if pplan is not None and not pplan.matches(nranks, data_axes):
        from .. import observability as _obs

        _obs.inc("placement.plan_skipped", reason="mesh_mismatch")
        pplan = None
    if pplan is not None and pplan.sharded_update \
            and (len(data_axes) != 1 or multiproc):
        # the plan's fused sharded update cannot run on this topology
        # — skip the plan WHOLESALE (never apply its bucket/strategy
        # half while silently dropping the update it was priced with)
        from .. import observability as _obs

        _obs.inc("placement.plan_skipped", reason="unsupported_topology")
        pplan = None
    quant = pplan.quant_mode if pplan is not None else quant_mode()
    use_sharded = (pplan.sharded_update if pplan is not None
                   else sharded_update_enabled(build_strategy))
    if use_sharded and len(data_axes) == 1 and not multiproc:
        apply_sharded_weight_update(program, scope, nranks,
                                    axis=data_axes[0], quant=quant)
    resync_sharded_state(program, scope)
    if pplan is not None:
        mb, plan, report = (pplan.bucket_mb, pplan.bucket_plan_mode,
                            pplan.report)
    else:
        mb = bucket_mb(build_strategy)
        plan = bucket_plan_mode()
        report = load_profile_report() if plan == "profile" else None
    if mb > 0:
        bucket_allreduce_ops(program, bucket_bytes=int(mb * (1 << 20)),
                             quant=quant, scope=scope, plan=plan,
                             report=report)
    elif quant != "none":
        # quantization without bucketing: rewrite per-grad allreduces
        # into single-member bucket ops so the payload still compresses
        bucket_allreduce_ops(program, bucket_bytes=0, quant=quant,
                             scope=scope)
    if getattr(program, "_placement_shaped", False):
        return  # shaping already baked in (steady-state: one getattr)
    program._placement_shaped = True
    from .scheduling import (async_collectives_enabled,
                             configure_bucket_quant,
                             quant_error_feedback, reduce_strategy_mode,
                             schedule_async_collectives,
                             swap_reduction_strategy)

    strategy = pplan.strategy if pplan is not None \
        else reduce_strategy_mode()
    if strategy != "ring":
        swap_reduction_strategy(program, strategy)
    ef = pplan.error_feedback if pplan is not None \
        else quant_error_feedback()
    qmodes = pplan.quant_buckets if pplan is not None else None
    if ef or qmodes:
        configure_bucket_quant(program, scope, nranks, data_axes[0],
                               modes=qmodes, error_feedback=ef)
    do_async = pplan.async_collectives if pplan is not None \
        else async_collectives_enabled()
    if do_async:
        schedule_async_collectives(program, report=report, scope=scope)
    if pplan is not None:
        program._placement_plan = pplan.summary()
        from .. import observability as _obs

        _obs.inc("placement.plan_applied")


# -- bucketed allreduce -----------------------------------------------------


def _pergrad_allreduce_indices(ops) -> List[int]:
    out = []
    for i, op in enumerate(ops):
        if op.type != "c_allreduce_sum":
            continue
        x, o = op.input("X"), op.output("Out")
        if len(x) == 1 and x == o:
            out.append(i)
    return out


def plan_buckets(items, bucket_bytes: int):
    """Greedy size-capped bucketing in availability order.

    ``items``: [(anchor, first_consumer, key, nbytes, idx)] sorted by
    anchor (the last op index that touches the grad before its
    allreduce — i.e. when the grad becomes available). A bucket closes
    when adding a member would blow the byte cap, change the (ring,
    dtype) key, or push the bucket's insertion point (max anchor + 1)
    past any member's first consumer. Returns a list of buckets, each
    {"members": [idx...], "anchor": int, "key": key}."""
    buckets: List[Dict] = []
    open_by_key: Dict = {}
    for anchor, first_use, key, nbytes, idx in sorted(items):
        b = open_by_key.get(key)
        if b is not None:
            new_anchor = max(b["anchor"], anchor)
            fits = (bucket_bytes > 0
                    and b["bytes"] + nbytes <= bucket_bytes)
            ordered = (new_anchor + 1 <= min(b["min_use"], first_use))
            if not (fits and ordered):
                b = None
        if b is None:
            b = {"members": [], "bytes": 0, "anchor": -1,
                 "min_use": first_use, "key": key}
            buckets.append(b)
            open_by_key[key] = b
        b["members"].append(idx)
        b["bytes"] += nbytes
        b["anchor"] = max(b["anchor"], anchor)
        b["min_use"] = min(b["min_use"], first_use)
    return buckets


def _fit_cost_model(report) -> Optional[Tuple[float, float]]:
    """(intercept_ms, ms_per_byte) fitted to the report's measured
    per-bucket collective costs — the cost model the profile-guided
    planner prices candidate buckets with. With one measured point the
    per-op latency and the bandwidth term cannot be separated; a small
    fixed floor (10% of the measured cost) stands in for the latency so
    the planner never treats splitting as free and shatters the plan
    back to per-grad."""
    pts = [(float(b.get("bytes") or 0), float(b.get("collective_ms") or 0))
           for b in report.get("per_bucket") or []
           if (b.get("collective_ms") or 0) > 0
           and (b.get("bytes") or 0) > 0]
    if not pts:
        return None
    xs = [p[0] for p in pts]
    ys = [p[1] for p in pts]
    if len(set(xs)) >= 2:
        n = float(len(pts))
        mx = sum(xs) / n
        my = sum(ys) / n
        var = sum((x - mx) ** 2 for x in xs)
        slope = sum((x - mx) * (y - my) for x, y in pts) / var
        icept = my - slope * mx
        if slope <= 0:   # degenerate fit (noise-dominated): fall back
            slope = my / mx if mx else 0.0
            icept = 0.0
        return max(0.0, icept), max(0.0, slope)
    icept = 0.1 * ys[0]
    slope = max(0.0, ys[0] - icept) / xs[0] if xs[0] else 0.0
    return icept, slope   # model reproduces the measured point


def plan_buckets_profile(items, report, bucket_bytes: int,
                         compute_pos) -> Optional[List[Dict]]:
    """Measurement-driven bucketing (DynaFlow-style: scheduling from
    measured operator timing, PAPERS.md).

    ``items`` is the same ``(anchor, first_use, key, nbytes, idx)``
    list ``plan_buckets`` takes; ``report`` a saved ``profile_step``
    report; ``compute_pos(op_index)`` maps an anchor to its position in
    the collective-free op sequence (the coordinate system the
    report's ``backward_segments`` measure — identical under any
    bucket plan, since only collective ops move).

    The rule the measurement drives: a bucket's predicted serial cost
    (fitted ``a + b*bytes`` model) must stay under
    ``PROFILE_PLAN_BUDGET_FRAC`` of the measured backward compute
    remaining after its availability point — the report's
    ``max_hideable_frac`` budget. Growing a bucket both raises its
    cost and (by dragging the anchor later) shrinks its budget, so
    buckets close exactly where the measurement says further
    coalescing would expose wire time; grads whose own budget is
    already ~zero (produced at the very end of backward — nothing left
    to hide behind) merge into one tail bucket per key, minimizing op
    count where overlap is impossible. The byte cap and the
    first-consumer ordering constraint still bind. Returns None when
    the report carries no usable cost model (caller falls back to the
    size plan)."""
    model = _fit_cost_model(report)
    segs = [s for s in (report.get("backward_segments") or [])
            if isinstance(s, (list, tuple)) and len(s) == 3]
    if model is None or not segs:
        return None
    icept, slope = model

    def cost(nbytes):
        return icept + slope * nbytes

    def hide(pos):
        return sum(float(ms) for _s, e, ms in segs if e > pos)

    frac = PROFILE_PLAN_BUDGET_FRAC
    buckets: List[Dict] = []
    open_by_key: Dict = {}
    tail_by_key: Dict = {}
    for anchor, first_use, key, nbytes, idx in sorted(items):
        pos = compute_pos(anchor)
        budget = hide(pos)
        hideable = budget > 0.0 and cost(nbytes) - icept < budget
        store = open_by_key if hideable else tail_by_key
        b = store.get(key)
        if b is not None:
            new_anchor = max(b["anchor"], anchor)
            # same cap contract as plan_buckets: bucket_bytes <= 0
            # means one bucket per grad (nothing ever coalesces)
            fits_cap = (bucket_bytes > 0
                        and b["bytes"] + nbytes <= bucket_bytes)
            ordered = (new_anchor + 1 <= min(b["min_use"], first_use))
            fits_budget = (not hideable) or (
                cost(b["bytes"] + nbytes)
                <= frac * hide(compute_pos(new_anchor)))
            if not (fits_cap and ordered and fits_budget):
                b = None
        if b is None:
            b = {"members": [], "bytes": 0, "anchor": -1,
                 "min_use": first_use, "key": key}
            buckets.append(b)
            store[key] = b
        b["members"].append(idx)
        b["bytes"] += nbytes
        b["anchor"] = max(b["anchor"], anchor)
        b["min_use"] = min(b["min_use"], first_use)
    return buckets


@checked_rewrite("bucket_allreduce")
def bucket_allreduce_ops(program, bucket_bytes: int = 4 << 20,
                         quant: str = "none", scope=None,
                         plan: str = "size", report=None) -> int:
    """Coalesce per-grad ``c_allreduce_sum`` ops into
    ``c_bucket_allreduce`` ops (one flat psum per bucket), hoisted to
    each bucket's availability point. Returns the number of bucket ops
    emitted (0 = nothing to do). ``bucket_bytes <= 0`` means "one
    bucket per grad" — used to apply quantization without coalescing.
    ``plan="profile"`` with a loaded ``report`` switches the boundary
    choice to ``plan_buckets_profile`` (falling back to the size plan
    when the report doesn't fit this program)."""
    if getattr(program, "_allreduce_bucketed", False):
        return 0
    program._allreduce_bucketed = True
    from .. import framework

    block = program.global_block()
    ops = block.ops
    cand = _pergrad_allreduce_indices(ops)
    if not cand or (len(cand) <= 1 and quant == "none"):
        return 0

    # one pass over the program: per-var sorted op-index lists, so each
    # candidate's anchor (last non-candidate toucher before it) and
    # first consumer resolve by bisection instead of an O(ops) rescan
    # per grad
    import bisect

    cand_set = set(cand)
    touched_at: Dict[str, List[int]] = {}
    consumed_at: Dict[str, List[int]] = {}
    for j, op in enumerate(ops):
        ins = op.input_arg_names
        for nm in ins:
            consumed_at.setdefault(nm, []).append(j)
        if j not in cand_set:
            for nm in set(ins) | set(op.output_arg_names):
                touched_at.setdefault(nm, []).append(j)

    items = []
    for i in cand:
        g = ops[i].input("X")[0]
        t = touched_at.get(g, ())
        k = bisect.bisect_left(t, i)
        last = t[k - 1] if k else -1
        c = consumed_at.get(g, ())
        k = bisect.bisect_right(c, i)
        use = c[k] if k < len(c) else len(ops)
        n, dtype = _numel_and_dtype(block, scope, g)
        if n is None:
            continue  # unknown payload: leave its per-grad op alone
        try:
            itemsize = np.dtype(dtype).itemsize if dtype else 4
        except TypeError:  # same tolerance as engine._var_nbytes
            itemsize = 4
        items.append((last, use, (ops[i].attrs.get("ring_id", 0), dtype),
                      n * itemsize, i))
    if not items:
        return 0

    mode_used = "size"
    buckets = None
    if plan == "profile" and report is not None:
        # positions in the collective-free op sequence — the report's
        # coordinate system; a report from a different program shape
        # (stale file, wrong model) is detected and ignored
        cpos = []
        k = 0
        for op in ops:
            cpos.append(k)
            if not op.type.startswith("c_"):
                k += 1
        if int(report.get("n_compute") or -1) == k:
            def compute_pos(anchor):
                if anchor < 0:
                    return 0
                p = cpos[anchor]
                return p + (0 if ops[anchor].type.startswith("c_") else 1)

            buckets = plan_buckets_profile(items, report, bucket_bytes,
                                           compute_pos)
            if buckets is not None:
                mode_used = "profile"
    if buckets is None:
        buckets = plan_buckets(items, bucket_bytes)
    from .. import observability as _obs

    _obs.inc("parallel.bucket_plan", mode=mode_used)
    program._bucket_plan = {
        "requested": plan, "mode": mode_used,
        "n_buckets": len(buckets),
        "bucket_bytes": [b["bytes"] for b in buckets],
        "anchors": [b["anchor"] for b in buckets],
    }
    removed = set()
    # bucket ops to splice in right AFTER the op at index `anchor`
    # (anchor -1 = before everything)
    after: Dict[int, List] = {}
    for b in buckets:
        names = [ops[i].input("X")[0] for i in b["members"]]
        rid = b["key"][0]
        ar = framework.Operator(
            block, "c_bucket_allreduce", {"X": names}, {"Out": names},
            {"ring_id": rid, "quant": quant, "use_calc_stream": True})
        ar._id = program._next_op_id()
        removed.update(b["members"])
        after.setdefault(b["anchor"], []).append(ar)

    new_ops = list(after.get(-1, []))
    for i, op in enumerate(ops):
        if i not in removed:
            new_ops.append(op)
        new_ops.extend(after.get(i, ()))
    block.ops = new_ops
    _bump_version(program)
    return len(buckets)


# -- cross-replica sharded weight update ------------------------------------


def _attrs_sig(attrs) -> Tuple:
    return tuple(sorted((k, repr(v)) for k, v in attrs.items()
                        if not k.startswith("_")))


def _splice_flat_state(block, scope, state_names, total, padded, dtype,
                       slot):
    """Concatenate the per-param accumulators named in ``state_names``
    (zeros where uninitialized) into one zero-padded flat array."""
    parts = []
    for sn in state_names:
        var = scope.find_var(sn)
        if var is not None and var.is_initialized():
            parts.append(np.asarray(var.raw().array).ravel())
        else:
            sv = block.var(sn)
            parts.append(np.zeros(int(np.prod(sv.shape)),
                                  dtype=np.dtype(dtype)))
    flat = np.concatenate(parts) if parts else np.zeros(0, np.dtype(dtype))
    if flat.size != total:
        raise ValueError(
            "sharded update: state %r totals %d elements, "
            "params total %d" % (slot, flat.size, total))
    return np.concatenate([flat, np.zeros(padded - total, flat.dtype)])


def _src_token(scope, name):
    """The var's current scope value OBJECT (None when absent or
    uninitialized): training never touches the retired per-param
    state vars, so a different object means something outside the
    mesh step — a startup re-run — re-initialized the var. The token
    holds the array itself (not its id), keeping it alive so a later
    allocation can never alias a freed array's address."""
    var = scope.find_var(name)
    if var is None or not var.is_initialized():
        return None
    return var.raw().array


def resync_sharded_state(program, scope) -> int:
    """Re-running the STARTUP program resets the retired per-param
    optimizer state vars but cannot see the flat ``sharded_update_*``
    vars it never knew about — a restarted job would silently keep its
    trained moments. Detect the restart (EVERY source var's array
    object replaced since the splice; a partial change is left alone —
    per-param values are stale by design after training) and rebuild
    the flat state from the freshly-initialized per-param values.
    Returns the number of flat vars rebuilt."""
    layout = getattr(program, "_sharded_flat_layout", None)
    if not layout:
        return 0
    tokens = program._sharded_src_tokens
    block = program.global_block()
    n = 0
    for flat_name, (srcs, total, padded, dtype, slot) in layout.items():
        cur = tuple(_src_token(scope, sn) for sn in srcs)
        old = tokens[flat_name]
        # vars uninitialized both then and now carry no signal either
        # way; every var WITH a signal must have been replaced
        signal = [(o, c) for o, c in zip(old, cur)
                  if o is not None or c is not None]
        if not signal or any(o is c for o, c in signal):
            continue
        scope.var(flat_name).get_tensor()._array = _splice_flat_state(
            block, scope, srcs, total, padded, dtype, slot)
        tokens[flat_name] = cur
        n += 1
    return n


@checked_rewrite("sharded_update")
def apply_sharded_weight_update(program, scope, nranks: int,
                                axis: str = "dp",
                                quant: str = "none") -> int:
    """Rewrite each (supported) optimizer instance's per-param
    (c_allreduce_sum, update-op) pairs into ONE ``c_sharded_update``
    op, and re-layout its optimizer state into flat vars sharded over
    ``axis`` (spec recorded in ``program._var_shard_specs``; existing
    scope values are spliced in flattened + zero-padded to a multiple
    of ``nranks``). Returns the number of groups rewritten.

    Grouping key: (op type, hyperparam attrs, LearningRate var, param
    dtype) — i.e. one group per optimizer instance per dtype. Params
    that are mesh-sharded (``_var_shard_specs``), use non-elementwise
    optimizers, or whose reduced grad has readers besides the update
    op keep their per-param path untouched.
    """
    prev = getattr(program, "_sharded_update_n", None)
    if prev is not None:
        if prev != nranks:
            raise ValueError(
                "program already sharded-update-rewritten for %d ranks, "
                "mesh now has %d" % (prev, nranks))
        return 0
    program._sharded_update_n = nranks
    from .. import framework

    block = program.global_block()
    ops = block.ops
    shard_specs = getattr(program, "_var_shard_specs", None) or {}
    cand = set(_pergrad_allreduce_indices(ops))
    grad_ar: Dict[str, int] = {ops[i].input("X")[0]: i for i in cand}
    consumed_at: Dict[str, List[int]] = {}
    for j, op in enumerate(ops):
        for nm in op.input_arg_names:
            consumed_at.setdefault(nm, []).append(j)
    groups: Dict[Tuple, List[int]] = {}
    for i, op in enumerate(ops):
        if op.type not in _SHARDABLE_OPTIMIZERS:
            continue
        p = op.input("Param")[0]
        pv = block._find_var_recursive(p)
        if (p in shard_specs or pv is None or not pv.shape
                or not all(isinstance(s, int) and s > 0 for s in pv.shape)
                or getattr(pv, "type", "lod_tensor") != "lod_tensor"):
            continue
        g = op.input("Grad")[0]
        gv = block._find_var_recursive(g)
        if gv is not None and getattr(gv, "type", "") == "selected_rows":
            continue  # sparse grads keep the row-wise per-param kernel
        ai = grad_ar.get(g)
        if ai is not None and any(j > ai and j != i
                                  for j in consumed_at.get(g, ())):
            # some other op reads the REDUCED grad after its allreduce
            # (grad clipping, a fetch op, ...); collapsing this pair
            # would delete the in-place reduction that reader relies
            # on — keep the param on the per-grad path
            continue
        key = (op.type, _attrs_sig(op.attrs),
               op.input("LearningRate")[0], str(pv.dtype))
        groups.setdefault(key, []).append(i)

    if not groups:
        return 0
    removed = set()
    # new group op spliced in at the position of the group's FIRST
    # optimizer op
    replace_at: Dict[int, object] = {}
    n_groups = 0
    for key, idxs in sorted(groups.items(), key=lambda kv: kv[1][0]):
        op_type, _, lr_name, dtype = key
        member_ops = [ops[i] for i in idxs]
        params = [op.input("Param")[0] for op in member_ops]
        grads = [op.input("Grad")[0] for op in member_ops]
        sizes = [int(np.prod(block.var(p).shape)) for p in params]
        total = sum(sizes)
        shard = -(-total // nranks)
        padded = shard * nranks
        n_groups += 1
        # content-derived name: scope vars are process-global, and a
        # per-program group counter would collide when two programs
        # with sharded updates share one Scope (e.g. a GAN's two
        # optimizers) — the digest of (op type, member params) keeps
        # distinct groups distinct and is stable across rebuilds
        sig = hashlib.sha1(("%s|%s" % (op_type, ",".join(
            "%s:%d" % t for t in zip(params, sizes)))).encode())
        gtag = sig.hexdigest()[:8]

        inputs = {"Param": params, "Grad": grads, "LearningRate": [lr_name]}
        outputs = {"ParamOut": params}
        for slot_key, slot in zip(("StateA", "StateB"),
                                  SHARDED_UPDATE_SLOTS[op_type]):
            state_names = [op.input(slot)[0] for op in member_ops]
            flat_name = "sharded_update_%s.%s" % (gtag, slot.lower())
            fv = block.create_var(name=flat_name, shape=(padded,),
                                  dtype=dtype, persistable=True)
            fv.stop_gradient = True
            # splice current accumulator values into the flat var,
            # zero-padded; retire the per-param vars (stale from here,
            # but remembered so resync_sharded_state can rebuild the
            # flat state when a startup re-run re-initializes them)
            flat = _splice_flat_state(block, scope, state_names,
                                      total, padded, dtype, slot)
            for sn in state_names:
                block.var(sn).persistable = False
            scope.var(flat_name).get_tensor()._array = flat
            for attr in ("_sharded_flat_layout", "_sharded_src_tokens"):
                if getattr(program, attr, None) is None:
                    setattr(program, attr, {})
            program._sharded_flat_layout[flat_name] = (
                tuple(state_names), total, padded, dtype, slot)
            program._sharded_src_tokens[flat_name] = tuple(
                _src_token(scope, sn) for sn in state_names)
            inputs[slot_key] = [flat_name]
            outputs[slot_key + "Out"] = [flat_name]
            specs = getattr(program, "_var_shard_specs", None)
            if specs is None:
                specs = {}
                program._var_shard_specs = specs
            specs[flat_name] = (axis,)
        for scalar in ("Beta1Pow", "Beta2Pow"):
            names = [op.input(scalar) for op in member_ops]
            if all(n for n in names):
                inputs[scalar] = [n[0] for n in names]
                outputs[scalar + "Out"] = [n[0] for n in names]

        attrs = dict(member_ops[0].attrs)
        attrs.update({"op_type": op_type, "shard_axis": axis,
                      "nranks": int(nranks), "padded_size": int(padded),
                      "quant": quant})
        su = framework.Operator(block, "c_sharded_update", inputs,
                                outputs, attrs)
        su._id = program._next_op_id()
        replace_at[idxs[0]] = su
        removed.update(idxs)
        removed.update(grad_ar[g] for g in grads if g in grad_ar)

    new_ops = []
    for i, op in enumerate(ops):
        if i in replace_at:
            new_ops.append(replace_at[i])
        if i not in removed:
            new_ops.append(op)
    block.ops = new_ops
    _merge_data_axes(program, (axis,))
    _bump_version(program)
    return n_groups


# -- steering registration ---------------------------------------------------
# The PR-10 profile-guided bucket planner, exposed through the shared
# `profile report → plan` registry (observability.steering) so every
# report consumer — this planner, the placement search, future serving
# / lazy-dygraph replanners — dispatches through ONE interface instead
# of growing private report plumbing.


def _steer_bucket_layout(report, items=None, bucket_bytes=4 << 20,
                         compute_pos=None, **_ctx):
    """``steer("bucket_layout", report, items=..., compute_pos=...)``
    → the measured bucket layout (``plan_buckets_profile``), or None
    when the report/context cannot drive a plan (callers fall back to
    the size plan)."""
    if report is None or items is None or compute_pos is None:
        return None
    return plan_buckets_profile(items, report, bucket_bytes, compute_pos)


from ..observability import steering as _steering  # noqa: E402

_steering.register_steerer(
    "bucket_layout", _steer_bucket_layout,
    "profile-guided gradient-bucket boundaries (PR 10)")
