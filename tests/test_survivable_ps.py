"""GB-scale survivable parameter server (ISSUE 8).

Covers: delta replication bit-for-bit against the full-blob path
(anchors + changed-var deltas + sparse row slices) with its
``ps.replication_bytes{mode=}`` / ``ps.delta_rounds`` /
``ps.anchor_rounds`` counters; incremental checkpoints (fingerprint
and content-hash shard reuse, load parity with full saves, corrupt
reused-shard fallback, ``checkpoint.delta_bytes`` /
``checkpoint.shards_reused``); lease-based promotion with quorum
(renewals keep a backup loyal, a dead primary's tombstone elects the
backup proactively, a partitioned control plane is quorum-DENIED —
at most one writable primary, an isolated >=3-group primary demotes
itself); async-mode round-gated replay (exactly-once across a
failover mid-async-push); key-range sharding (routing, endpoint
groups, row ranges, the two-phase round barrier, a shard primary's
death leaving the sister shard bit-for-bit intact); the ``partition``
fault primitive; and chaos-schedule determinism for the new modes."""
import os
import socket
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _eps(n):
    return ["127.0.0.1:%d" % _free_port() for _ in range(n)]


class MiniScope(dict):
    def local_var_names(self):
        return list(self)


class MiniExec:
    def _read_var(self, scope, name):
        return scope.get(name)

    def _write_var(self, scope, name, val):
        scope[name] = np.asarray(val)

    def run_block(self, block, scope):
        block(scope)


def _sgd_block(scope, lr=0.1):
    scope["w"] = scope["w"] - lr * scope["w@GRAD"]


def _grad(tid, rnd, dim=4):
    return np.full(dim, (tid + 1) * 0.01 * rnd, dtype=np.float32)


def _fast_env(monkeypatch):
    monkeypatch.setenv("PADDLE_PS_CONNECT_TIMEOUT", "1")
    monkeypatch.setenv("PADDLE_PS_FAILOVER_CONNECT_TIMEOUT", "1")
    monkeypatch.setenv("PADDLE_PS_RPC_RETRIES", "2")
    monkeypatch.setenv("PADDLE_PS_RPC_BACKOFF_MS", "10")
    monkeypatch.setenv("PADDLE_PS_RPC_DEADLINE", "20")


def _mk_ps(eps, i, fanin=1, sync=True, ballast=0, **kw):
    from paddle_tpu.distributed.ps_rpc import PSServer

    scope = MiniScope()
    scope["w"] = np.zeros(4, dtype=np.float32)
    if ballast:
        scope["ballast"] = np.zeros(ballast, dtype=np.float32)
    server = PSServer(eps[i], MiniExec(), scope,
                      {"w@GRAD": _sgd_block}, fanin=fanin,
                      sync_mode=sync, endpoints=eps, **kw)
    server.start_background()
    return server, scope


# -- delta replication -------------------------------------------------------


def _train(eps, rounds, tid=0):
    from paddle_tpu.distributed.ps_rpc import PSClient

    c = PSClient(",".join(eps), trainer_id=tid)
    w = None
    for rnd in range(1, rounds + 1):
        c.send_grad("w@GRAD", _grad(tid, rnd))
        c.send_barrier()
        w = c.get_param("w")
        c.fetch_barrier()
    c.close()
    return w


def test_delta_replication_bitwise_vs_full(monkeypatch):
    """The same 5-round workload replicated twice — anchors-only
    (anchor_every=1: every round a full blob) vs delta mode
    (anchor_every=3) — must leave the BACKUP bit-for-bit identical,
    with the delta run recording delta rounds whose bytes are
    strictly below the anchors' (the ballast var never changes, so
    deltas exclude it)."""
    from paddle_tpu import observability as obs

    _fast_env(monkeypatch)

    def run(anchor_every):
        eps = _eps(2)
        s0, sc0 = _mk_ps(eps, 0, ballast=4096,
                         anchor_every=anchor_every)
        s1, sc1 = _mk_ps(eps, 1, ballast=4096,
                         anchor_every=anchor_every)
        try:
            _train(eps, rounds=5)
            np.testing.assert_array_equal(np.asarray(sc0["w"]),
                                          np.asarray(sc1["w"]))
            return (np.asarray(sc1["w"]).tobytes(),
                    np.asarray(sc1["ballast"]).tobytes())
        finally:
            s0.stop()
            s1.stop()

    d0 = obs.counter_value("ps.delta_rounds") or 0
    a0 = obs.counter_value("ps.anchor_rounds") or 0
    db0 = obs.counter_value("ps.replication_bytes", mode="delta") or 0
    fb0 = obs.counter_value("ps.replication_bytes", mode="full") or 0
    full_run = run(anchor_every=1)
    anchors_after = (obs.counter_value("ps.anchor_rounds") or 0) - a0
    assert anchors_after == 5, "anchor_every=1 must ship 5 full blobs"
    assert (obs.counter_value("ps.delta_rounds") or 0) == d0
    delta_run = run(anchor_every=3)
    assert delta_run == full_run, \
        "delta and full replication must converge bit-for-bit"
    d_rounds = (obs.counter_value("ps.delta_rounds") or 0) - d0
    assert d_rounds == 3, \
        "anchor_every=3 over 5 rounds = anchors at 1,3 + 3 deltas"
    d_bytes = (obs.counter_value("ps.replication_bytes", mode="delta")
               or 0) - db0
    f_bytes = (obs.counter_value("ps.replication_bytes", mode="full")
               or 0) - fb0
    assert 0 < d_bytes < f_bytes, (d_bytes, f_bytes)
    # the per-round delta excludes the 16KB ballast entirely
    assert d_bytes / d_rounds < 4096 * 4, d_bytes


def test_delta_row_slice_for_push_sparse(monkeypatch):
    """Async push_sparse marks only the touched rows dirty: after the
    first (anchor) ship, a later push replicates a ROW SLICE of the
    table — bytes ~ rows touched, not table size — and the backup's
    table still matches the primary's bit-for-bit."""
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed.ps_rpc import PSClient, PSServer

    _fast_env(monkeypatch)
    eps = _eps(2)
    height, width = 128, 4

    class SparseExec(MiniExec):
        def _write_var(self, scope, name, val):
            scope[name] = val  # keep SelectedRows grads un-coerced

    def mk(i):
        scope = MiniScope()
        scope["emb"] = np.zeros((height, width), dtype=np.float32)

        def sparse_block(scope):
            g = scope["emb@GRAD"]
            rows = np.asarray(g.rows(), dtype=np.int64)
            vals = np.asarray(g._value)
            emb = np.array(scope["emb"], copy=True)
            emb[rows] -= 0.1 * vals  # row-local, like pslib sgd
            scope["emb"] = emb

        s = PSServer(eps[i], SparseExec(), scope,
                     {"emb@GRAD": sparse_block}, fanin=1,
                     sync_mode=False, endpoints=eps)
        s.start_background()
        return s, scope

    s0, sc0 = mk(0)
    s1, sc1 = mk(1)
    monkeypatch.setattr(s0, "_async_repl_every", 1)  # ship every push
    try:
        c = PSClient(",".join(eps), trainer_id=0)
        c.push_sparse("emb@GRAD", [3, 7],
                      np.ones((2, width), "f4"), param="emb")
        db0 = obs.counter_value("ps.replication_bytes",
                                mode="delta") or 0
        c.push_sparse("emb@GRAD", [5],
                      np.full((1, width), 2.0, "f4"), param="emb")
        d_bytes = (obs.counter_value("ps.replication_bytes",
                                     mode="delta") or 0) - db0
        assert 0 < d_bytes <= 4 * width * 4, \
            "second push must ship a row slice, got %d bytes" % d_bytes
        np.testing.assert_array_equal(np.asarray(sc0["emb"]),
                                      np.asarray(sc1["emb"]))
        assert np.asarray(sc1["emb"])[5, 0] == np.float32(-0.2)
        c.close()
    finally:
        s0.stop()
        s1.stop()


# -- incremental checkpoints -------------------------------------------------


def test_incremental_checkpoint_parity_and_fallback(tmp_path):
    """save_incremental == save bit-for-bit on load; a fingerprint
    match skips even PRODUCING the shard; corrupting a reused shard
    (the torn-write replace case) falls back to the previous
    checkpoint; counters record the reuse."""
    from paddle_tpu import observability as obs
    from paddle_tpu.checkpoint import CheckpointManager, verify_manifest

    big = os.urandom(1 << 15)
    full = CheckpointManager(str(tmp_path / "full"), keep=3)
    inc = CheckpointManager(str(tmp_path / "inc"), keep=3)

    def writer(step):
        def w(d):
            with open(os.path.join(d, "state.bin"), "wb") as f:
                f.write(b"round-%d" % step)
            with open(os.path.join(d, "ballast.bin"), "wb") as f:
                f.write(big)
        return w

    r0 = obs.counter_value("checkpoint.shards_reused") or 0
    d0 = obs.counter_value("checkpoint.delta_bytes") or 0
    for step in (1, 2, 3):
        full.save(step, writer(step))
        inc.save_incremental(
            step, {"state.bin": b"round-%d" % step,
                   "ballast.bin": _must_not_run if step > 1 else big},
            fingerprints={"ballast.bin": "static-v1"})
    assert (obs.counter_value("checkpoint.shards_reused") - r0) == 2
    fresh = (obs.counter_value("checkpoint.delta_bytes") or 0) - d0
    assert fresh == len(big) + 3 * len(b"round-N"), fresh

    def load(mgr):
        out = {}

        def loader(d):
            verify_manifest(d)
            for fn in ("state.bin", "ballast.bin"):
                with open(os.path.join(d, fn), "rb") as f:
                    out[fn] = f.read()
        step = mgr.load_latest(loader)
        return step, out

    assert load(full) == load(inc), \
        "incremental and full checkpoints must load identically"

    # content-hash reuse without a fingerprint still links
    r1 = obs.counter_value("checkpoint.shards_reused")
    inc.save_incremental(4, {"state.bin": b"round-4",
                             "ballast.bin": big})
    assert obs.counter_value("checkpoint.shards_reused") - r1 == 1

    # corrupt the newest REUSED shard (replace: the torn-write case,
    # which breaks the hardlink) -> load falls back one rotation
    p = str(tmp_path / "inc" / "ckpt-4" / "ballast.bin")
    os.remove(p)
    with open(p, "wb") as f:
        f.write(b"garbage")
    step, out = load(inc)
    assert step == 3 and out["ballast.bin"] == big


def _must_not_run():
    raise AssertionError("fingerprint-matched shard was produced")


def test_manifest_extra_roundtrips_shard_map(tmp_path):
    """A trainer checkpoints its adopted shard map as manifest
    ``extra``; ``manifest_extra`` hands it back (advisory — an
    unreadable manifest degrades to {}, never an error)."""
    from paddle_tpu.checkpoint import CheckpointManager, manifest_extra

    mgr = CheckpointManager(str(tmp_path / "c"), keep=2)
    smap = {"version": 2, "overrides": {"w0": 1}}
    mgr.save_incremental(1, {"state.bin": b"x"},
                         extra={"shard_map": smap})
    d = mgr.dir_for(1)
    got = manifest_extra(d)
    assert got.get("shard_map") == smap
    assert manifest_extra(str(tmp_path / "nope")) == {}


# -- lease + quorum promotion ------------------------------------------------


def test_lease_renewals_keep_backup_loyal(monkeypatch):
    """While the primary renews, the backup never promotes (no lease
    expiry, no election) and a FRESH client walking into the backup is
    redirected to the primary, exactly as before."""
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed.ps_rpc import PSClient

    _fast_env(monkeypatch)
    eps = _eps(2)
    s0, sc0 = _mk_ps(eps, 0, lease_ms=300)
    s1, _ = _mk_ps(eps, 1, lease_ms=300)
    r0 = obs.counter_value("ps.lease_renewals") or 0
    try:
        time.sleep(1.2)  # 4 lease periods
        assert not s1._promoted, "backup promoted under live renewals"
        assert (obs.counter_value("ps.lease_renewals") or 0) > r0
        c = PSClient("%s,%s" % (eps[1], eps[0]), trainer_id=0)
        c.send_grad("w@GRAD", _grad(0, 1))
        c.send_barrier()
        assert c.endpoint == eps[0], "fresh client not redirected"
        assert not s1._promoted
        c.get_param("w")
        c.fetch_barrier()
        c.close()
    finally:
        s0.stop()
        s1.stop()


def test_dead_primary_tombstone_elects_backup_proactively(monkeypatch):
    """A SIGKILL-equivalent (stopped listener => connection REFUSED)
    lets the backup win its election on the tombstone quorum WITHOUT
    any client traffic — promotion is proactive under leases."""
    from paddle_tpu import observability as obs

    _fast_env(monkeypatch)
    eps = _eps(2)
    s0, _ = _mk_ps(eps, 0, lease_ms=300)
    s1, _ = _mk_ps(eps, 1, lease_ms=300)
    e0 = obs.counter_value("ps.lease_expiries", shard="0") or 0
    try:
        time.sleep(0.5)  # at least one renewal lands
        s0.stop()
        deadline = time.time() + 5
        while not s1._promoted and time.time() < deadline:
            time.sleep(0.05)
        assert s1._promoted, "tombstone quorum never promoted backup"
        assert s1._epoch >= 1, "promotion must bump the epoch"
        assert (obs.counter_value("ps.lease_expiries", shard="0")
                or 0) > e0
    finally:
        s0.stop()
        s1.stop()


def test_partitioned_backup_is_quorum_denied(monkeypatch):
    """Control-plane partition (every lease/vote rpc times out): the
    backup's lease expires but its elections gather neither a grant
    nor a tombstone — quorum denied, NO promotion, and the primary
    (2-endpoint group: no rival quorum can form without it) keeps
    serving. Exactly one writable primary."""
    from paddle_tpu.distributed import ps_rpc

    _fast_env(monkeypatch)
    eps = _eps(2)
    s0, _ = _mk_ps(eps, 0, lease_ms=300)
    s1, _ = _mk_ps(eps, 1, lease_ms=300)

    def severed(endpoint, msg, timeout=1.0):
        raise socket.timeout("partitioned control plane")

    try:
        time.sleep(0.5)  # healthy renewals first
        monkeypatch.setattr(ps_rpc, "_bare_rpc", severed)
        time.sleep(1.5)  # 5 lease periods of failed elections
        assert not s1._promoted, \
            "partition must never yield a second primary"
        assert s0._active_role(), "2-endpoint primary must serve on"
        assert s1._promised_epoch == 0 or not s1._promoted
    finally:
        s0.stop()
        s1.stop()


def test_isolated_primary_of_three_demotes(monkeypatch):
    """In a group of >= 3 a primary that cannot renew with a majority
    for a full lease steps down: behind its partition, the two backups
    COULD have elected a rival — better a loud redirect than split
    brain."""
    from paddle_tpu.distributed import ps_rpc

    _fast_env(monkeypatch)
    eps = _eps(3)

    def severed(endpoint, msg, timeout=1.0):
        raise socket.timeout("partitioned control plane")

    monkeypatch.setattr(ps_rpc, "_bare_rpc", severed)
    s0, _ = _mk_ps(eps, 0, lease_ms=300)
    try:
        deadline = time.time() + 5
        while s0._active_role() and time.time() < deadline:
            time.sleep(0.05)
        assert not s0._active_role(), \
            "isolated 3-group primary must demote within ~a lease"
    finally:
        s0.stop()


def test_legacy_instant_promotion_when_lease_disabled(monkeypatch):
    """PADDLE_PS_LEASE_MS=0 restores the ISSUE-4 contract: a genuinely
    failed-over client (fo >= 1) promotes the backup instantly; no
    lease threads run."""
    from paddle_tpu.distributed.ps_rpc import PSClient

    _fast_env(monkeypatch)
    eps = _eps(2)
    s0, _ = _mk_ps(eps, 0, fanin=1, lease_ms=0)
    s1, sc1 = _mk_ps(eps, 1, fanin=1, lease_ms=0)
    try:
        c = PSClient(",".join(eps), trainer_id=0)
        c.send_grad("w@GRAD", _grad(0, 1))
        c.send_barrier()
        c.get_param("w")
        c.fetch_barrier()
        s0.stop()
        t0 = time.time()
        c.send_grad("w@GRAD", _grad(0, 2))
        c.send_barrier()
        w = c.get_param("w")
        c.fetch_barrier()
        assert s1._promoted
        exp = {"w": np.zeros(4, "f4"), "w@GRAD": _grad(0, 1)}
        _sgd_block(exp)
        exp["w@GRAD"] = _grad(0, 2)
        _sgd_block(exp)
        np.testing.assert_array_equal(w, exp["w"])
        assert time.time() - t0 < 15
        c.close()
    finally:
        s0.stop()
        s1.stop()


# -- async-mode round-gated replay -------------------------------------------


def test_async_failover_round_gated_exactly_once(monkeypatch):
    """Async (RunAsyncLoop) mode with backups: every K applied ops the
    primary ships a synthetic round, acks tag each op with the round
    carrying it, and the client prunes its replay log by durable
    round. Killing the primary mid-stream and finishing on the backup
    applies every op EXACTLY once — bit-for-bit with the sequential
    oracle — and the replay log never grows past one round."""
    from paddle_tpu.distributed.ps_rpc import PSClient

    _fast_env(monkeypatch)
    eps = _eps(2)
    s0, sc0 = _mk_ps(eps, 0, sync=False, lease_ms=300)
    s1, sc1 = _mk_ps(eps, 1, sync=False, lease_ms=300)
    monkeypatch.setattr(s0, "_async_repl_every", 4)
    monkeypatch.setattr(s1, "_async_repl_every", 4)
    grads = [np.full(4, 0.01 * (i + 1), dtype=np.float32)
             for i in range(11)]
    try:
        c = PSClient(",".join(eps), trainer_id=0)
        for g in grads[:6]:
            c.send_grad("w@GRAD", g)
        # ops 1-4 shipped as round 1 and PRUNED; 5,6 still pending
        assert len(c._replay_log) == 2, \
            [e[2] for e in c._replay_log]
        s0.stop()
        for g in grads[6:]:
            c.send_grad("w@GRAD", g)
        w = c.get_param("w")
        c.close()
        oracle = {"w": np.zeros(4, "f4")}
        for g in grads:
            oracle["w@GRAD"] = g
            _sgd_block(oracle)
        assert w.tobytes() == oracle["w"].tobytes(), \
            "async failover lost or double-applied a push"
        np.testing.assert_array_equal(np.asarray(sc1["w"]),
                                      oracle["w"])
    finally:
        s0.stop()
        s1.stop()


def test_async_durable_round_requires_an_acked_backup(monkeypatch):
    """A ship that reached NOBODY must not advance durable_round: with
    the backup dead, the client's replay log keeps every unreplicated
    op — pruning them would lose pushes that exist only on the
    primary."""
    from paddle_tpu.distributed.ps_rpc import PSClient

    _fast_env(monkeypatch)
    eps = _eps(2)
    s0, _ = _mk_ps(eps, 0, sync=False, lease_ms=0)
    s1, _ = _mk_ps(eps, 1, sync=False, lease_ms=0)
    monkeypatch.setattr(s0, "_async_repl_every", 2)
    try:
        c = PSClient(",".join(eps), trainer_id=0)
        c.send_grad("w@GRAD", _grad(0, 1))
        c.send_grad("w@GRAD", _grad(0, 2))  # round 1 ships, acked
        assert not c._replay_log, "acked round must prune"
        s1.stop()  # the only backup dies: ships reach nobody
        for rnd in range(3, 9):
            c.send_grad("w@GRAD", _grad(0, rnd))
        assert len(c._replay_log) == 6, \
            "unacked ships must not prune the replay log"
        c.close()
    finally:
        s0.stop()
        s1.stop()


# -- key-range sharding ------------------------------------------------------


def test_shard_routing_stable_and_grad_follows_param():
    from paddle_tpu.distributed.ps_shard import (shard_for_key,
                                                 shard_for_rows,
                                                 row_range,
                                                 split_endpoint_groups)

    assert shard_for_key("w", 1) == 0
    for n in (2, 3, 8):
        for name in ("w", "emb/table", "fc_0.w_0"):
            s = shard_for_key(name, n)
            assert 0 <= s < n
            assert shard_for_key(name, n) == s, "routing must be stable"
            assert shard_for_key(name + "@GRAD", n) == s
            assert shard_for_key(name + "@MOMENTUM", n) == s
    # every shard of a 2-way split is reachable by SOME var name
    hit = {shard_for_key("w%d" % i, 2) for i in range(32)}
    assert hit == {0, 1}

    groups = split_endpoint_groups(["a:1", "b:2", "c:3", "d:4"], 2)
    assert groups == [["a:1", "b:2"], ["c:3", "d:4"]]
    with pytest.raises(ValueError, match="divisible"):
        split_endpoint_groups(["a:1", "b:2", "c:3"], 2)

    # contiguous row ranges tile the table exactly
    height = 103
    for n in (2, 4):
        edges = [row_range(s, height, n) for s in range(n)]
        assert edges[0][0] == 0 and edges[-1][1] == height
        for (a, b), (c, d) in zip(edges, edges[1:]):
            assert b == c
        owner = shard_for_rows(np.arange(height), height, n)
        for s, (lo, hi) in enumerate(edges):
            assert (owner[lo:hi] == s).all()


def _mk_group(eps, name, fanin=1, **kw):
    """One shard group's servers, all serving var ``name``."""
    from paddle_tpu.distributed.ps_rpc import PSServer

    out = []
    for ep in eps:
        scope = MiniScope()
        scope[name] = np.zeros(4, dtype=np.float32)

        def block(scope, _n=name):
            scope[_n] = scope[_n] - 0.1 * scope[_n + "@GRAD"]

        s = PSServer(ep, MiniExec(), scope, {name + "@GRAD": block},
                     fanin=fanin, endpoints=eps, **kw)
        s.start_background()
        out.append((s, scope))
    return out


def _shard_var_names(nshards):
    from paddle_tpu.distributed.ps_shard import shard_for_key

    names = []
    for s in range(nshards):
        i = 0
        while True:
            cand = "w%d" % i
            if (shard_for_key(cand, nshards) == s
                    and cand not in names):
                names.append(cand)
                break
            i += 1
    return names


def test_sharded_two_phase_barrier_and_shard_failover(monkeypatch):
    """2 key-range shards x (primary+backup): the two-phase barrier
    keeps every sub-client's replay log alive until EVERY shard acked;
    killing shard 0's primary mid-run fails over that shard alone and
    BOTH shards' params finish bit-for-bit against the per-var
    oracle."""
    from paddle_tpu.distributed.ps_shard import ShardedPSClient

    _fast_env(monkeypatch)
    names = _shard_var_names(2)
    g0, g1 = _eps(2), _eps(2)
    shard0 = _mk_group(g0, names[0], lease_ms=300)
    shard1 = _mk_group(g1, names[1], lease_ms=300)
    rounds, kill_at = 4, 2
    try:
        c = ShardedPSClient([",".join(g0), ",".join(g1)],
                            trainer_id=0)
        assert [c.shard_of(n) for n in names] == [0, 1]
        ws = {}
        for rnd in range(1, rounds + 1):
            for vi, name in enumerate(names):
                c.send_grad(name + "@GRAD", _grad(0, rnd) + vi)
            # phase-1/phase-2 contract: the logs hold the round until
            # EVERY shard acks
            assert all(len(sc._replay_log) == 1 for sc in c.shards)
            c.send_barrier()
            assert all(not sc._replay_log for sc in c.shards), \
                "commit must clear every shard's log"
            for name in names:
                ws[name] = c.get_param(name)
            c.fetch_barrier()
            if rnd == kill_at:
                shard0[0][0].stop()  # shard 0 primary dies; shard 1
                # must never notice
        for vi, name in enumerate(names):
            exp = {"w": np.zeros(4, "f4")}
            for rnd in range(1, rounds + 1):
                exp["w@GRAD"] = _grad(0, rnd) + vi
                _sgd_block(exp)
            assert ws[name].tobytes() == exp["w"].tobytes(), name
        assert shard0[1][0]._promoted, "shard 0 backup not promoted"
        assert not shard1[1][0]._promoted, \
            "shard 1 backup must be untouched"
        assert c.shards[1]._failover_count == 0
        c.close()
    finally:
        for s, _ in shard0 + shard1:
            s.stop()


def test_sharded_sparse_row_range_pull_push(monkeypatch):
    """pull/push_sparse with GLOBAL row ids: rows split by contiguous
    range, each shard holding its slice under LOCAL ids, results
    reassembled in request order."""
    from paddle_tpu.distributed.ps_rpc import PSServer
    from paddle_tpu.distributed.ps_shard import (ShardedPSClient,
                                                 row_range)

    _fast_env(monkeypatch)
    height, width, nshards = 10, 3, 2
    eps = _eps(2)
    servers = []
    for s in range(nshards):
        lo, hi = row_range(s, height, nshards)
        scope = MiniScope()
        scope["emb"] = (np.arange(lo, hi, dtype=np.float32)
                        .reshape(-1, 1) * np.ones((1, width), "f4"))
        srv = PSServer(eps[s], MiniExec(), scope, {}, fanin=1,
                       endpoints=[eps[s]])
        srv.start_background()
        servers.append(srv)
    try:
        c = ShardedPSClient([eps[0], eps[1]], trainer_id=0)
        ids = [7, 1, 9, 0, 4]  # deliberately out of order, both shards
        vals = c.pull_sparse("emb", ids, height=height)
        np.testing.assert_array_equal(
            vals, np.asarray(ids, "f4").reshape(-1, 1)
            * np.ones((1, width), "f4"))
        empty = c.pull_sparse("emb", [], height=height)
        assert empty.shape == (0, width) and empty.dtype == np.float32
        c.close()
    finally:
        for s in servers:
            s.stop()


# -- external quorum witness (ISSUE 13) --------------------------------------


def test_witness_blocks_forged_tombstones_then_allows_real_death(
        monkeypatch):
    """The N>=3 forged-tombstone corner: every group peer of the
    candidate answers connection-REFUSED (forgeable positive-death
    evidence) while the primary is ALIVE and still renewing with the
    witness — the witness denies, so the election must fail. Stop the
    primary for real and the witness's lease view expires: the next
    election wins on a genuine witness grant."""
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed import ps_rpc
    from paddle_tpu.distributed.ps_rpc import PSWitness

    _fast_env(monkeypatch)
    eps = _eps(3)
    wit_ep = _eps(1)[0]
    witness = PSWitness(wit_ep)
    witness.start_background()
    real_bare = ps_rpc._bare_rpc

    def forged(endpoint, msg, timeout=1.0):
        if endpoint == wit_ep:
            return real_bare(endpoint, msg, timeout)
        if msg.get("kind") in ("vote", "lease_renew") \
                and msg.get("candidate") == eps[1]:
            # only the CANDIDATE's probes see forged refusals; the
            # primary's own renewals to the group stay real
            raise ConnectionRefusedError("forged tombstone")
        return real_bare(endpoint, msg, timeout)

    s0, _ = _mk_ps(eps, 0, lease_ms=300, witnesses=[wit_ep])
    s1, _ = _mk_ps(eps, 1, lease_ms=300, witnesses=[wit_ep])
    s2, _ = _mk_ps(eps, 2, lease_ms=300, witnesses=[wit_ep])
    v0 = obs.counter_value("ps.witness_votes", shard="0") or 0
    try:
        time.sleep(0.5)  # renewals reach group + witness
        # backup 1's lease view never refreshes; its group probes are
        # forged-refused — only the witness answers honestly
        monkeypatch.setattr(s1, "_refresh_lease_locked",
                            lambda epoch: None)
        monkeypatch.setattr(ps_rpc, "_bare_rpc", forged)
        s1._lease_deadline = time.monotonic() - 1.0
        deadline = time.time() + 2.0
        while time.time() < deadline:
            assert not s1._promoted, \
                "forged tombstones elected a backup under a live " \
                "primary despite the witness"
            time.sleep(0.05)
        assert (obs.counter_value("ps.witness_votes", shard="0")
                or 0) > v0, "the election never consulted the witness"
        # now the primary REALLY dies: renewals to the witness stop,
        # its lease view expires, the grant flows, promotion happens
        monkeypatch.setattr(ps_rpc, "_bare_rpc", real_bare)
        s0.stop()
        deadline = time.time() + 6.0
        while not (s1._promoted or s2._promoted) \
                and time.time() < deadline:
            time.sleep(0.05)
        assert s1._promoted or s2._promoted, \
            "real death + expired witness view must still promote"
    finally:
        s0.stop()
        s1.stop()
        s2.stop()
        witness.stop()


def test_vote_regrant_same_candidate_survives_lost_reply(monkeypatch):
    """votedFor semantics (found by the --migrate drill under an
    injected reply drop): a voter — group peer or witness — that
    granted an epoch must RE-GRANT the same epoch to the SAME
    candidate, or a lost grant reply burns the epoch and livelocks
    every election retry. A rival at the consumed epoch stays
    denied."""
    from paddle_tpu.distributed.ps_rpc import PSWitness

    _fast_env(monkeypatch)
    eps = _eps(2)
    s0, _ = _mk_ps(eps, 0, lease_ms=300)
    s1, _ = _mk_ps(eps, 1, lease_ms=300)
    try:
        s1._lease_deadline = time.monotonic() - 1.0  # expired voter
        vote = {"kind": "vote", "epoch": 1, "cand_round": 99,
                "candidate": "cand:A"}
        r1, _ = s1._handle(dict(vote), b"")
        assert r1["granted"]
        r2, _ = s1._handle(dict(vote), b"")  # grant reply "lost"
        assert r2["granted"], "re-vote by the promise holder denied"
        rb, _ = s1._handle(dict(vote, candidate="cand:B"), b"")
        assert not rb["granted"], "rival stole a consumed epoch"
        r3, _ = s1._handle(dict(vote, epoch=2,
                                candidate="cand:B"), b"")
        assert r3["granted"], "higher epoch must still win the voter"
    finally:
        s0.stop()
        s1.stop()

    w = PSWitness(_eps(1)[0])
    try:
        wvote = {"kind": "vote", "epoch": 1, "shard": "7",
                 "lease_ms": 50, "candidate": "cand:A"}
        w._shard_state_locked("7", 50)["deadline"] = \
            time.monotonic() - 1.0
        g1, _ = w._handle(dict(wvote), b"")
        assert g1["granted"]
        w._state["7"]["deadline"] = time.monotonic() - 1.0
        g2, _ = w._handle(dict(wvote), b"")
        assert g2["granted"], "witness re-vote denied after lost reply"
        gb, _ = w._handle(dict(wvote, candidate="cand:B"), b"")
        assert not gb["granted"]
    finally:
        w.stop()


# -- clock-jitter chaos (ISSUE 13) -------------------------------------------


def test_clock_jitter_rule_parses_and_is_deterministic():
    import random as _random

    from paddle_tpu.distributed import fault

    rules = fault.parse_plan("clock_jitter:0.5:600,send.drop:0.1")
    assert rules[0].kind == "clock_jitter" and rules[0].param == 600.0
    with pytest.raises(ValueError, match="magnitude"):
        fault.parse_plan("clock_jitter:0.5")
    # repr round-trips
    assert fault.parse_plan(repr(rules[0]))[0].param == 600.0
    # per-process skew: seeded by (seed x identity), reproducible,
    # different identities wander differently
    prev = fault.get_identity()
    try:
        fault.set_identity("a:1")
        i1 = fault.FaultInjector(
            fault.parse_plan("clock_jitter:0:500"), seed=3)
        i2 = fault.FaultInjector(
            fault.parse_plan("clock_jitter:0:500"), seed=3)
        assert i1.clock_skew_s() == i2.clock_skew_s()
        assert abs(i1.clock_skew_s()) <= 0.5
        fault.set_identity("b:2")
        i3 = fault.FaultInjector(
            fault.parse_plan("clock_jitter:0:500"), seed=3)
        assert i3.clock_skew_s() != i1.clock_skew_s()
    finally:
        fault.set_identity(prev)
    # random_plan wiring: appended after the legacy draws
    base = fault.random_plan(_random.Random(11))
    withj = fault.random_plan(_random.Random(11), clock_jitter_ms=300)
    assert withj.startswith(base) and "clock_jitter:0.5:300" in withj
    fault.parse_plan(withj)
    # frame faults are untouched by a jitter-only plan
    inj = fault.FaultInjector(fault.parse_plan("clock_jitter:1:100"))
    assert not inj.rules and not inj.partitions
    assert len(inj.clock_rules) == 1


def test_clock_jitter_2x_lease_never_splits_the_brain(monkeypatch):
    """±2x-lease clock jitter on every participant: the backup's
    lease view may expire spuriously, but its elections stay
    quorum-gated (the live primary denies; in a 2-group no rival
    quorum can form without it) — no promotion, exactly one writable
    primary, training bit-for-bit."""
    from paddle_tpu.distributed import fault
    from paddle_tpu.distributed.ps_rpc import PSClient

    _fast_env(monkeypatch)
    monkeypatch.setenv("PADDLE_TPU_FAULTS", "clock_jitter:0.5:600")
    monkeypatch.setenv("PADDLE_TPU_FAULT_SEED", "9")
    fault.reset_injector()
    eps = _eps(2)
    try:
        s0, sc0 = _mk_ps(eps, 0, lease_ms=300)
        s1, _ = _mk_ps(eps, 1, lease_ms=300)
        try:
            c = PSClient(",".join(eps), trainer_id=0)
            w = None
            for rnd in range(1, 7):
                c.send_grad("w@GRAD", _grad(0, rnd), round=rnd)
                c.send_barrier(round=rnd)
                w = c.get_param("w")
                c.fetch_barrier()
                assert s0._active_role() and not s1._promoted, \
                    "jitter alone promoted a backup under a live " \
                    "primary"
                time.sleep(0.15)
            exp = {"w": np.zeros(4, "f4")}
            for rnd in range(1, 7):
                exp["w@GRAD"] = _grad(0, rnd)
                _sgd_block(exp)
            assert w.tobytes() == exp["w"].tobytes()
            assert (fault.get_injector() is not None
                    and fault.get_injector().clock_rules)
            c.close()
        finally:
            s0.stop()
            s1.stop()
    finally:
        fault.reset_injector()


# -- sharded eviction: disagreeing per-shard fanin (ISSUE 13) ----------------


def test_stale_round_guard_drops_resent_applied_round(monkeypatch):
    """A fresh incarnation re-running a TRAINING round the server
    already applied (its dead predecessor's barrier closed it) must
    be dropped — grads NOT folded into the next round, barriers NOT
    pre-paying the next fanin."""
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed.ps_rpc import PSClient

    _fast_env(monkeypatch)
    eps = _eps(1)
    s, scope = _mk_ps(eps, 0, fanin=1)
    st0 = obs.counter_value("ps.stale_rounds") or 0
    try:
        c1 = PSClient(eps[0], trainer_id=0)
        for rnd in (1, 2):
            c1.send_grad("w@GRAD", _grad(0, rnd), round=rnd)
            c1.send_barrier(round=rnd)
            c1.get_param("w")
            c1.fetch_barrier()
        c1.close()  # incarnation 1 "dies" after round 2 applied
        c2 = PSClient(eps[0], trainer_id=0)  # fresh cid, resumes at 2
        c2.send_grad("w@GRAD", _grad(0, 2), round=2)
        c2.send_barrier(round=2)  # both stale: dropped, not counted
        assert (obs.counter_value("ps.stale_rounds") or 0) >= st0 + 2
        assert s._applied_round == 2 and not s._pending
        c2.send_grad("w@GRAD", _grad(0, 3), round=3)
        c2.send_barrier(round=3)
        w = c2.get_param("w")
        c2.fetch_barrier()
        c2.close()
        exp = {"w": np.zeros(4, "f4")}
        for rnd in (1, 2, 3):
            exp["w@GRAD"] = _grad(0, rnd)
            _sgd_block(exp)
        assert w.tobytes() == exp["w"].tobytes(), \
            "stale-round resend leaked into a later round"
    finally:
        s.stop()


def test_sharded_eviction_disagreeing_fanin_reconciles(monkeypatch):
    """The drill case, in-process and fully pinned: trainer 1's
    round-1 grads reach BOTH shards but its phase-1 barrier reaches
    shard A only, then it dies. A (no eviction) applies round 1 with
    t1's barrier; B (evicting) evicts t1 and applies round 1 too —
    with t1's PENDING grads, so round 1 is complete everywhere. The
    disagreement bites at round 2: B (fanin shrunk to 1) applies it
    with t0 alone while A waits; the relaunched incarnation re-runs
    rounds 1-2 — stale-DROPPED exactly where they already applied —
    and genuinely contributes where they did not. Deterministic
    oracles: shard A's var = full 2-trainer history; shard B's var =
    full minus t1's round-2 grad; round 3 complete on both (t1
    re-admitted, fanin restored). Without the stale-round guard,
    t1's re-sent round-1 barrier would pre-pay B's round-3 fanin and
    apply it with a stale grad mix."""
    import threading as _threading

    from paddle_tpu.distributed.ps_rpc import PSClient

    _fast_env(monkeypatch)
    names = _shard_var_names(2)
    epA, epB = _eps(1), _eps(1)
    rounds = 3

    def mk(eps_one, name, evict_after):
        from paddle_tpu.distributed.ps_rpc import PSServer

        scope = MiniScope()
        scope[name] = np.zeros(4, dtype=np.float32)
        s = PSServer(eps_one[0], MiniExec(), scope,
                     {name + "@GRAD": _sgd_factory(name + "@GRAD")},
                     fanin=2, endpoints=eps_one,
                     evict_after=evict_after)
        s.start_background()
        return s, scope

    sA, scA = mk(epA, names[0], evict_after=0.0)   # never evicts
    sB, scB = mk(epB, names[1], evict_after=0.6)   # evicts t1
    servers = [sA, sB]

    def t0_loop(out):
        cA = PSClient(epA[0], trainer_id=0)
        cB = PSClient(epB[0], trainer_id=0)
        for rnd in range(1, rounds + 1):
            cA.send_grad(names[0] + "@GRAD", _grad(0, rnd), round=rnd)
            cB.send_grad(names[1] + "@GRAD", _grad(0, rnd), round=rnd)

            def barrier(c, rnd=rnd):
                c.send_barrier(round=rnd)
            tb = [_threading.Thread(target=barrier, args=(c,))
                  for c in (cA, cB)]
            for t in tb:
                t.start()
            for t in tb:
                t.join(timeout=30)
            out[names[0]] = cA.get_param(names[0])
            out[names[1]] = cB.get_param(names[1])
            cA.fetch_barrier()
            cB.fetch_barrier()
        cA.close()
        cB.close()

    # incarnation 1 of t1: grads to BOTH shards, barrier to A ONLY
    c1A = PSClient(epA[0], trainer_id=1)
    c1B = PSClient(epB[0], trainer_id=1)
    c1A.send_grad(names[0] + "@GRAD", _grad(1, 1), round=1)
    c1B.send_grad(names[1] + "@GRAD", _grad(1, 1), round=1)
    out = {}
    t0 = _threading.Thread(target=t0_loop, args=(out,))
    t0.start()
    barA = _threading.Thread(
        target=lambda: c1A.send_barrier(round=1))
    barA.start()
    barA.join(timeout=20)  # A applies round 1 with BOTH trainers
    c1A.close()
    c1B.close()  # t1 dead; B must evict it to finish round 1

    def t1_incarnation2():
        time.sleep(1.2)  # past B's eviction window
        cA = PSClient(epA[0], trainer_id=1)
        cB = PSClient(epB[0], trainer_id=1)
        for rnd in range(1, rounds + 1):  # re-runs round 1 (stale)
            cA.send_grad(names[0] + "@GRAD", _grad(1, rnd), round=rnd)
            cB.send_grad(names[1] + "@GRAD", _grad(1, rnd), round=rnd)
            tb = [_threading.Thread(
                target=lambda c=c, r=rnd: c.send_barrier(round=r))
                for c in (cA, cB)]
            for t in tb:
                t.start()
            for t in tb:
                t.join(timeout=30)
            cA.get_param(names[0])
            cB.get_param(names[1])
            cA.fetch_barrier()
            cB.fetch_barrier()
        cA.close()
        cB.close()

    t1v2 = _threading.Thread(target=t1_incarnation2)
    t1v2.start()
    t0.join(timeout=60)
    t1v2.join(timeout=60)
    try:
        assert not t0.is_alive() and not t1v2.is_alive(), \
            "reconciliation deadlocked"
        # shard A: every round had both trainers
        expA = np.zeros(4, dtype=np.float32)
        for rnd in range(1, rounds + 1):
            expA = expA - np.float32(0.1) * (_grad(0, rnd)
                                             + _grad(1, rnd))
        np.testing.assert_array_equal(np.asarray(scA[names[0]]), expA)
        # shard B: round 1 complete (t1's grads were pending when the
        # eviction applied it); round 2 sailed with t0 only; round 3
        # complete again (t1 re-admitted). The stale resends of
        # rounds 1-2 were dropped, never mixed into round 3.
        expB = np.zeros(4, dtype=np.float32)
        for rnd in range(1, rounds + 1):
            tot = _grad(0, rnd) if rnd == 2 \
                else _grad(0, rnd) + _grad(1, rnd)
            expB = expB - np.float32(0.1) * tot
        np.testing.assert_array_equal(np.asarray(scB[names[1]]), expB)
    finally:
        for s in servers:
            s.stop()


# -- GB-scale measurement harness (ISSUE 13) ---------------------------------


def test_ps_scale_bench_smoke(tmp_path):
    """The measurement harness end to end (smoke table): incremental
    digesting strictly cheaper than full re-hash per round, delta
    bytes under 1% of the anchor, bench_diff-compatible record."""
    import subprocess
    import sys as _sys

    out = str(tmp_path / "ps_scale.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PADDLE_TPU_FAULTS", None)
    r = subprocess.run(
        [_sys.executable, os.path.join(REPO, "tools",
                                       "ps_scale_bench.py"),
         "--smoke", "--rounds", "3", "--out", out],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    import json

    rec = json.load(open(out))
    cfg = rec["configs"]["ps_scale"]
    assert cfg["ps_digest_ms"] < cfg["ps_digest_full_ms"]
    assert 0 < cfg["repl_delta_bytes_per_round"] \
        < 0.01 * cfg["repl_anchor_bytes"]
    assert cfg["rounds_per_s"] > 0
    # the record diffs cleanly through the perf gate
    r2 = subprocess.run(
        [_sys.executable, os.path.join(REPO, "tools",
                                       "bench_diff.py"), out, out],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "ps_digest_ms" in r2.stdout


# -- the partition fault primitive -------------------------------------------


class _PeerSock:
    def __init__(self, peer):
        self._peer = peer
        self.sent = []

    def getpeername(self):
        host, port = self._peer.rsplit(":", 1)
        return (host, int(port))

    def sendall(self, b):
        self.sent.append(bytes(b))


def test_partition_rule_parses_and_matches_pairs():
    from paddle_tpu.distributed.fault import FaultRule, parse_plan

    rules = parse_plan("partition:1:127.0.0.1:7001|127.0.0.1:7002,"
                       "send.drop:0.1")
    assert rules[0].kind == "partition" and rules[0].prob == 1.0
    assert rules[0].param == "127.0.0.1:7001|127.0.0.1:7002"
    assert rules[0].partition_peer("127.0.0.1:7001") == "127.0.0.1:7002"
    assert rules[0].partition_peer("127.0.0.1:7002") == "127.0.0.1:7001"
    assert rules[0].partition_peer("127.0.0.1:9999") is None
    assert rules[0].partition_peer(None) is None
    single = parse_plan("any.partition:0.5:127.0.0.1:7003")[0]
    assert single.partition_peer(None) == "127.0.0.1:7003"
    with pytest.raises(ValueError, match="peer"):
        parse_plan("partition:1")
    # round-trips through repr
    assert parse_plan(repr(rules[0]))[0].param == rules[0].param


def test_partition_injector_blackholes_both_directions():
    """A pair rule severs frames on sockets to the peer — send AND
    recv — only in processes whose identity is one of the pair; a
    third party's traffic to either endpoint is untouched."""
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed import fault

    a, b = "127.0.0.1:7001", "127.0.0.1:7002"
    inj = fault.FaultInjector(
        fault.parse_plan("partition:1:%s|%s" % (a, b)), seed=1)
    prev = fault.get_identity()
    n0 = obs.counter_value("fault.injected", side="send",
                           kind="partition") or 0
    try:
        fault.set_identity(a)
        s = _PeerSock(b)
        assert inj.on_send(s, b"frame") is False and not s.sent
        assert inj.on_recv(_PeerSock(b)) == "drop"
        other = _PeerSock("127.0.0.1:9999")
        assert inj.on_send(other, b"frame") is True and other.sent
        # a process OUTSIDE the pair (a trainer) is never severed
        fault.set_identity("127.0.0.1:5555")
        s2 = _PeerSock(b)
        assert inj.on_send(s2, b"frame") is True and s2.sent
        assert (obs.counter_value("fault.injected", side="send",
                                  kind="partition") or 0) == n0 + 1
    finally:
        fault.set_identity(prev)


def test_random_plan_partition_wiring():
    import random as _random

    from paddle_tpu.distributed.fault import parse_plan, random_plan

    base = random_plan(_random.Random(11))
    withp = random_plan(_random.Random(11),
                        partition_peers=["h:1|h:2", "h:3|h:4"])
    assert withp.startswith(base), \
        "peers must not perturb the legacy rng draws"
    assert "partition:1:" in withp
    rules = parse_plan(withp)
    assert rules[-1].kind == "partition"
    assert rules[-1].param in ("h:1|h:2", "h:3|h:4")


def test_chaos_schedule_deterministic_for_sharded_modes():
    import sys

    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos_drill

    a = chaos_drill.make_schedule(77, 6, shards=2, partition=True)
    assert a == chaos_drill.make_schedule(77, 6, shards=2,
                                          partition=True)
    assert a["shards"] == 2 and a["partition"]
    assert a["die_shard"] in (0, 1)
    assert a["partition_shard"] == (a["die_shard"] + 1) % 2
    legacy = chaos_drill.make_schedule(77, 6)
    # legacy draws unchanged: same plan and kill points
    assert legacy["plan"] == a["plan"]
    assert legacy["trainer_kill_round"] == a["trainer_kill_round"]
    assert legacy["partition_shard"] is None
    # ISSUE 13 modes: deterministic, legacy-draw-compatible
    m = chaos_drill.make_schedule(77, 8, shards=2, migrate=True)
    assert m == chaos_drill.make_schedule(77, 8, shards=2,
                                          migrate=True)
    assert m["migrate_from"] == m["die_shard"]
    assert m["migrate_to"] == (m["die_shard"] + 1) % 2
    assert 1 <= m["migrate_round"] <= 4
    assert m["plan"] == chaos_drill.make_schedule(77, 8,
                                                  shards=2)["plan"]
    e = chaos_drill.make_schedule(77, 6, shards=2, evict=True)
    assert e == chaos_drill.make_schedule(77, 6, shards=2, evict=True)
    assert e["evict_shard"] == 1
    assert e["trainer_kill_round"] <= 4


# -- chunk-level + incremental digests (ISSUE 13) ----------------------------


def _plan_server(eps, scope_vars, **kw):
    """A PSServer whose _replication_plan we drive directly (no
    backups — planning is pure given the scope + dirty state)."""
    from paddle_tpu.distributed.ps_rpc import PSServer

    scope = MiniScope()
    scope.update(scope_vars)
    s = PSServer(eps[0], MiniExec(), scope, {}, fanin=1,
                 endpoints=[eps[0]], **kw)
    return s, scope


def _prime(server):
    """First plan = anchor; adopt its digests as the shipped state."""
    arrays = server._scope_arrays()
    mode, items, digests = server._replication_plan(arrays)
    assert mode == "full"
    server._shipped_digests = digests
    server._dirty_rows.clear()
    server._dirty_dense.clear()
    server._applied_round += 1  # off the anchor cadence
    return digests


def _plan_bytes(items):
    return sum(a.nbytes for _, a, _ in items)


def test_one_row_update_to_256mb_var_ships_under_one_percent():
    """The ISSUE-13 acceptance bound: a single-row touch of a >=256MB
    dense var ships < 1% of the full-var bytes — via a row slice when
    the rows are known, via CHUNK slices when only the digest knows
    (dense-dirty, rows lost)."""
    from paddle_tpu.distributed import ps_rpc

    height, width = 262144, 256  # 256 MiB float32
    big = np.zeros((height, width), dtype=np.float32)
    s, scope = _plan_server(_eps(1), {"big": big},
                            anchor_every=1000000)
    try:
        _prime(s)
        full_bytes = big.nbytes
        # rows-known path (push_sparse tracked the touch)
        scope["big"][12345, :] = 7.0
        s._dirty_rows["big"] = {12345}
        mode, items, digests = s._replication_plan(s._scope_arrays())
        assert mode == "delta"
        assert items and items[0][2] == {"rows": [12345]}
        assert _plan_bytes(items) < 0.01 * full_bytes
        s._shipped_digests = digests
        s._dirty_rows.clear()
        # rows-UNKNOWN path (dense-dirty): the chunk digests localize
        # the change to one chunk of the flat stream
        scope["big"][200000, :] = 9.0
        s._dirty_dense.add("big")
        mode, items, digests = s._replication_plan(s._scope_arrays())
        assert mode == "delta"
        assert items and "chunk" in (items[0][2] or {})
        shipped = _plan_bytes(items)
        assert shipped < 0.01 * full_bytes, shipped
        ce = ps_rpc._chunk_elems_for(big)
        assert shipped <= 2 * ce * 4  # ~one chunk (straddle-safe)
    finally:
        s.stop()


def test_chunk_boundary_straddling_dirty_row():
    """A dirty row whose byte range straddles a chunk boundary must
    re-hash and ship BOTH chunks; the backup splice must be
    bit-for-bit."""
    import paddle_tpu.distributed.ps_rpc as ps_rpc

    rows = ps_rpc._chunks_for_rows(
        [1], np.zeros((4, 6), "f4"), 8)  # row 1 = elems 6..11
    assert rows == {0, 1}
    assert ps_rpc._chunks_for_rows([0], np.zeros((4, 6), "f4"), 8) \
        == {0}
    assert ps_rpc._chunks_for_rows([3], np.zeros((4, 6), "f4"), 8) \
        == {2}

    # end to end with a tiny chunk size: the straddled update ships
    # two chunk slices and the backup matches bit-for-bit
    prev = os.environ.pop("PADDLE_PS_DIGEST_CHUNK_MB", None)
    os.environ["PADDLE_PS_DIGEST_CHUNK_MB"] = str(32 / (1 << 20))
    try:
        tbl = np.arange(24, dtype=np.float32).reshape(4, 6)
        s, scope = _plan_server(_eps(1), {"t": tbl.copy()},
                                anchor_every=1000000)
        try:
            d0 = _prime(s)
            assert len(d0["t"]["chunks"]) == 3  # 24 elems / 8
            # rows KNOWN: the straddled row re-hashes chunks 0+1
            # incrementally (chunk 2 carried over) and ships the
            # smaller ROW slice
            scope["t"][1, :] += 100.0
            s._dirty_rows["t"] = {1}
            mode, items, d1 = s._replication_plan(s._scope_arrays())
            assert mode == "delta"
            assert items[0][2] == {"rows": [1]}  # row beats chunk
            assert d1["t"]["chunks"][0] != d0["t"]["chunks"][0]
            assert d1["t"]["chunks"][1] != d0["t"]["chunks"][1]
            assert d1["t"]["chunks"][2] == d0["t"]["chunks"][2]
            s._shipped_digests = d1
            s._dirty_rows.clear()
            # rows UNKNOWN (dense-dirty): the same straddling change
            # ships ONE contiguous chunk run covering both chunks,
            # and the flat splice is bit-for-bit
            scope["t"][1, :] += 1.0
            s._dirty_dense.add("t")
            before = np.frombuffer(
                tbl.tobytes(), dtype=np.float32).copy()
            before.reshape(4, 6)[1, :] += 100.0  # the shipped state
            mode, items, _ = s._replication_plan(s._scope_arrays())
            assert mode == "delta"
            ranges = [it[2]["chunk"] for it in items]
            assert ranges == [[0, 16]], ranges
            got = before.copy()
            for _, arr, extra in items:
                lo, hi = extra["chunk"]
                got[lo:hi] = arr.reshape(-1)
            assert got.reshape(4, 6).tobytes() \
                == np.asarray(scope["t"]).tobytes()
        finally:
            s.stop()
    finally:
        if prev is None:
            os.environ.pop("PADDLE_PS_DIGEST_CHUNK_MB", None)
        else:
            os.environ["PADDLE_PS_DIGEST_CHUNK_MB"] = prev


def test_chunk_size_larger_than_var_degenerates_to_whole_var():
    s, scope = _plan_server(_eps(1), {"w": np.zeros(8, "f4")},
                            anchor_every=1000000)
    try:
        d = _prime(s)
        assert len(d["w"]["chunks"]) == 1  # one chunk covers the var
        scope["w"][3] = 5.0
        s._dirty_dense.add("w")
        mode, items, _ = s._replication_plan(s._scope_arrays())
        assert mode == "delta"
        # single-chunk vars ship WHOLE (no chunk header)
        assert len(items) == 1 and items[0][2] is None
        assert items[0][1].nbytes == 32
    finally:
        s.stop()


def test_digest_state_resets_after_anchor_and_skips_untouched():
    """Anchors re-hash EVERYTHING (incremental skips cannot drift past
    an anchor); between anchors an untouched var is neither re-hashed
    (ps.digest_vars{mode=skipped}) nor shipped, and its carried-over
    digest still detects a later change."""
    from paddle_tpu import observability as obs

    s, scope = _plan_server(_eps(1),
                            {"w": np.zeros(8, "f4"),
                             "ballast": np.zeros(64, "f4")},
                            anchor_every=1000000)
    try:
        sk0 = obs.counter_value("ps.digest_vars", mode="skipped") or 0
        _prime(s)
        # round 1: only w touched -> ballast skipped, not shipped
        scope["w"][0] = 1.0
        s._dirty_dense.add("w")
        mode, items, digests = s._replication_plan(s._scope_arrays())
        assert mode == "delta"
        assert [n for n, _, _ in items] == ["w"]
        assert (obs.counter_value("ps.digest_vars", mode="skipped")
                or 0) > sk0
        s._shipped_digests = digests
        s._dirty_dense.clear()
        # force an anchor: everything re-hashed + shipped, fresh state
        s._applied_round = 0
        s._anchor_every = 1
        prev_ballast = digests["ballast"]
        mode, items, digests = s._replication_plan(s._scope_arrays())
        assert mode == "full" and len(items) == 2
        assert digests["ballast"] is not prev_ballast  # re-hashed
        assert digests["ballast"]["chunks"] \
            == prev_ballast["chunks"]  # same content, same digest
        s._shipped_digests = digests
        s._anchor_every = 1000000
        s._applied_round = 1
        # the carried digest still catches a change with NO dirty info
        # when incremental digesting is off for that var (dense-dirty)
        scope["ballast"][5] = 3.0
        s._dirty_dense.add("ballast")
        mode, items, _ = s._replication_plan(s._scope_arrays())
        assert [n for n, _, _ in items] == ["ballast"]
    finally:
        s.stop()


def test_incremental_digest_bitwise_parity_with_optimizer_family(
        monkeypatch):
    """A momentum-style block touches w AND w@MOM: the family-dirty
    contract must re-hash the companions too, leaving the backup
    bit-for-bit identical under PADDLE_PS_INCR_DIGEST=1 vs =0."""
    from paddle_tpu.distributed.ps_rpc import PSClient, PSServer

    _fast_env(monkeypatch)

    def run(incr):
        monkeypatch.setenv("PADDLE_PS_INCR_DIGEST",
                           "1" if incr else "0")
        eps = _eps(2)
        servers = []
        for ep in eps:
            scope = MiniScope()
            scope["w"] = np.zeros(4, dtype=np.float32)
            scope["w@MOM"] = np.zeros(4, dtype=np.float32)
            scope["ballast"] = np.zeros(512, dtype=np.float32)

            def mom_block(sc):
                sc["w@MOM"] = (np.float32(0.9) * sc["w@MOM"]
                               + sc["w@GRAD"])
                sc["w"] = sc["w"] - np.float32(0.1) * sc["w@MOM"]

            s = PSServer(ep, MiniExec(), scope,
                         {"w@GRAD": mom_block}, fanin=1,
                         endpoints=eps, anchor_every=4)
            s.start_background()
            servers.append((s, scope))
        try:
            c = PSClient(",".join(eps), trainer_id=0)
            for rnd in range(1, 7):
                c.send_grad("w@GRAD", _grad(0, rnd), round=rnd)
                c.send_barrier(round=rnd)
                c.get_param("w")
                c.fetch_barrier()
            c.close()
            bsc = servers[1][1]
            return (np.asarray(bsc["w"]).tobytes(),
                    np.asarray(bsc["w@MOM"]).tobytes())
        finally:
            for s, _ in servers:
                s.stop()

    assert run(True) == run(False), \
        "incremental digesting diverged the backup's optimizer family"


# -- live shard migration (ISSUE 13) -----------------------------------------


def _sgd_factory(gname):
    base = gname.split("@", 1)[0]

    def blk(scope):
        scope[base] = scope[base] - np.float32(0.1) * scope[gname]
    return blk


def _mk_migration_fixture(monkeypatch, lease_ms=400, extra_var=False):
    """2 shards x (primary+backup), one var per shard (plus an extra
    donor var when asked), block factories armed for adoption."""
    from paddle_tpu.distributed.ps_rpc import PSServer
    from paddle_tpu.distributed.ps_shard import ShardedPSClient

    _fast_env(monkeypatch)
    names = _shard_var_names(2)
    groups = [_eps(2), _eps(2)]
    servers = []
    for si, grp in enumerate(groups):
        for ep in grp:
            scope = MiniScope()
            scope[names[si]] = np.zeros(4, dtype=np.float32)
            g2b = {names[si] + "@GRAD": _sgd_factory(
                names[si] + "@GRAD")}
            if extra_var and si == 0:
                scope["extra0"] = np.zeros(4, dtype=np.float32)
                g2b["extra0@GRAD"] = _sgd_factory("extra0@GRAD")
            s = PSServer(ep, MiniExec(), scope, g2b, fanin=1,
                         endpoints=grp, lease_ms=lease_ms, shard=si,
                         block_factory=_sgd_factory)
            s.start_background()
            servers.append((s, scope))
    c = ShardedPSClient([",".join(g) for g in groups], trainer_id=0)
    return names, groups, servers, c


def test_live_migration_end_to_end(monkeypatch):
    """Happy path: migrate shard 0's var to shard 1 mid-training —
    map bumps atomically at the barrier, params stay oracle-exact,
    the recipient's BACKUP holds the var before the donor drops it,
    a fresh (version-0) client self-repairs via wrong_shard, and the
    donor group keeps answering barriers for its empty range."""
    names, groups, servers, c = _mk_migration_fixture(monkeypatch)
    from paddle_tpu.distributed.ps_shard import ShardedPSClient

    rounds = 6
    try:
        ws = {}
        for rnd in range(1, rounds + 1):
            for vi, n in enumerate(names):
                c.send_grad(n + "@GRAD", _grad(0, rnd) + vi,
                            round=rnd)
            c.send_barrier(round=rnd)
            ws = {n: c.get_param(n) for n in names}
            c.fetch_barrier()
            if rnd == 2:
                r = c.migrate(names[0], 1)
                assert r.get("pending")
        assert c.map_version == 1
        assert c.map_overrides == {names[0]: 1}
        for vi, n in enumerate(names):
            exp = {"w": np.zeros(4, "f4")}
            for rnd in range(1, rounds + 1):
                exp["w@GRAD"] = _grad(0, rnd) + vi
                _sgd_block(exp)
            assert ws[n].tobytes() == exp["w"].tobytes(), n
        # recipient backup holds it; donor group dropped it
        assert names[0] in servers[3][1]
        assert names[0] not in servers[0][1]
        assert names[0] not in servers[1][1]
        # a fresh hash-routed client self-repairs via wrong_shard
        c2 = ShardedPSClient([",".join(g) for g in groups],
                             trainer_id=1)
        got = c2.get_param(names[0])
        assert got.tobytes() == ws[names[0]].tobytes()
        assert c2.map_version == 1
        c2.close()
    finally:
        c.close()
        for s, _ in servers:
            s.stop()


def test_migration_replay_original_tokens_exactly_once(monkeypatch):
    """The watermark shipped with the install makes a replay of an
    rpc ALREADY FOLDED into the migrated state answer `replayed` at
    the recipient — exactly-once across the shard-map version bump —
    and a donor-primary death right after migration fails over with
    original-token replays, finishing oracle-exact."""
    names, groups, servers, c = _mk_migration_fixture(monkeypatch,
                                                      extra_var=True)
    rounds, kill_at = 6, 4
    allv = names + ["extra0"]
    try:
        ws = {}
        for rnd in range(1, rounds + 1):
            for vi, n in enumerate(allv):
                c.send_grad(n + "@GRAD", _grad(0, rnd) + vi,
                            round=rnd)
            c.send_barrier(round=rnd)
            ws = {n: c.get_param(n) for n in allv}
            c.fetch_barrier()
            if rnd == 2:
                c.migrate(names[0], 1)
            if rnd == kill_at:
                servers[0][0].stop()  # donor primary dies post-
                # migration; its backup must serve the remaining var
        for vi, n in enumerate(allv):
            exp = {"w": np.zeros(4, "f4")}
            for rnd in range(1, rounds + 1):
                exp["w@GRAD"] = _grad(0, rnd) + vi
                _sgd_block(exp)
            assert ws[n].tobytes() == exp["w"].tobytes(), n
        assert servers[1][0]._promoted
        # the exactly-once mechanism itself: a replay of a PRE-
        # MIGRATION rpc (the donor sub-client's folded seq) at the
        # RECIPIENT answers `replayed` without executing
        donor_cid = c.shards[0]._cid
        recipient = servers[2][0] if servers[2][0]._active_role() \
            else servers[3][0]
        resp, _ = recipient._dispatch(
            {"kind": "send_grad", "cid": donor_cid, "seq": 1,
             "round": 0, "name": names[0] + "@GRAD",
             "array": {"dtype": "float32", "shape": [4]}},
            np.zeros(4, "f4").tobytes())
        assert resp.get("replayed"), resp
    finally:
        c.close()
        for s, _ in servers:
            s.stop()


def test_migration_ships_optimizer_family(monkeypatch):
    """A momentum-optimized var migrates WITH its @-companions: the
    recipient's rebuilt block finds w@MOM exactly where the donor
    left it, and the training history stays oracle-exact across the
    move. (Without family shipping, the rebuilt block would crash or
    silently restart momentum from zero.)"""
    from paddle_tpu.distributed.ps_rpc import PSServer
    from paddle_tpu.distributed.ps_shard import ShardedPSClient

    _fast_env(monkeypatch)
    names = _shard_var_names(2)

    def mom_factory(gname):
        base = gname.split("@", 1)[0]

        def blk(sc):
            sc[base + "@MOM"] = (np.float32(0.9) * sc[base + "@MOM"]
                                 + sc[gname])
            sc[base] = sc[base] - np.float32(0.1) * sc[base + "@MOM"]
        return blk

    groups = [_eps(2), _eps(2)]
    servers = []
    for si, grp in enumerate(groups):
        for ep in grp:
            scope = MiniScope()
            scope[names[si]] = np.zeros(4, dtype=np.float32)
            scope[names[si] + "@MOM"] = np.zeros(4, dtype=np.float32)
            s = PSServer(ep, MiniExec(), scope,
                         {names[si] + "@GRAD": mom_factory(
                             names[si] + "@GRAD")},
                         fanin=1, endpoints=grp, lease_ms=400,
                         shard=si, block_factory=mom_factory)
            s.start_background()
            servers.append((s, scope))
    c = ShardedPSClient([",".join(g) for g in groups], trainer_id=0)
    rounds = 6
    try:
        ws = {}
        for rnd in range(1, rounds + 1):
            for vi, n in enumerate(names):
                c.send_grad(n + "@GRAD", _grad(0, rnd) + vi,
                            round=rnd)
            c.send_barrier(round=rnd)
            ws = {n: c.get_param(n) for n in names}
            c.fetch_barrier()
            if rnd == 2:
                c.migrate(names[0], 1)
        assert c.map_version == 1
        for vi, n in enumerate(names):
            w = np.zeros(4, dtype=np.float32)
            mom = np.zeros(4, dtype=np.float32)
            for rnd in range(1, rounds + 1):
                mom = np.float32(0.9) * mom + (_grad(0, rnd) + vi)
                w = w - np.float32(0.1) * mom
            assert ws[n].tobytes() == w.tobytes(), \
                "%s diverged — optimizer state lost in migration" % n
        # the companion physically lives on the recipient now
        assert names[0] + "@MOM" in servers[2][1] \
            or names[0] + "@MOM" in servers[3][1]
        assert names[0] + "@MOM" not in servers[0][1]
    finally:
        c.close()
        for s, _ in servers:
            s.stop()


def test_migration_reinstalls_when_recipient_lost_the_stage(
        monkeypatch):
    """The recipient-kill window: the staged family is memory-only,
    so a recipient primary dying between install and commit loses it
    — the donor (which still holds the state; that is why the hard
    commit waits) must RE-INSTALL on the promoted recipient and drive
    the commit home."""
    from paddle_tpu import observability as obs

    names, groups, servers, c = _mk_migration_fixture(monkeypatch)
    donor_primary = servers[0][0]
    recipient_primary = servers[2][0]
    real_mig_client = donor_primary._mig_client
    state = {"dropped": False}

    class _DropFirstCommit:
        def __init__(self, inner):
            self._inner = inner

        def _call(self, msg, raw=b""):
            if msg.get("kind") == "migrate_commit" \
                    and not state["dropped"]:
                # simulate the recipient primary dying right after
                # the install: its promoted backup has no stage
                state["dropped"] = True
                with recipient_primary._lock:
                    recipient_primary._staged_in.clear()
                raise OSError("recipient primary died before commit")
            return self._inner._call(msg, raw)

    monkeypatch.setattr(
        donor_primary, "_mig_client",
        lambda chain: _DropFirstCommit(real_mig_client(chain)))
    cr0 = obs.counter_value("ps.migrations",
                            outcome="commit_retry") or 0
    rounds = 6
    try:
        ws = {}
        for rnd in range(1, rounds + 1):
            for vi, n in enumerate(names):
                c.send_grad(n + "@GRAD", _grad(0, rnd) + vi,
                            round=rnd)
            c.send_barrier(round=rnd)
            ws = {n: c.get_param(n) for n in names}
            c.fetch_barrier()
            if rnd == 2:
                c.migrate(names[0], 1)
        assert state["dropped"], "the failure was never injected"
        assert (obs.counter_value("ps.migrations",
                                  outcome="commit_retry") or 0) > cr0
        assert c.map_version == 1 and c.map_overrides == {names[0]: 1}
        assert names[0] in servers[2][1], \
            "re-install never reached the recipient"
        for vi, n in enumerate(names):
            exp = {"w": np.zeros(4, "f4")}
            for rnd in range(1, rounds + 1):
                exp["w@GRAD"] = _grad(0, rnd) + vi
                _sgd_block(exp)
            assert ws[n].tobytes() == exp["w"].tobytes(), n
    finally:
        c.close()
        for s, _ in servers:
            s.stop()


def test_migrate_begin_refuses_second_pending_var(monkeypatch):
    """One in-flight migration per group: a second migrate_begin for
    a DIFFERENT var before the barrier executes the first is refused
    loudly, never silently replacing the acked intent."""
    names, groups, servers, c = _mk_migration_fixture(
        monkeypatch, extra_var=True)
    try:
        r = c.migrate(names[0], 1)
        assert r.get("pending")
        with pytest.raises(RuntimeError, match="already pending"):
            c.migrate("extra0", 1)
    finally:
        c.close()
        for s, _ in servers:
            s.stop()


def test_migration_install_failure_rolls_back(monkeypatch):
    """Unreachable recipient: bounded install retries, then ROLLBACK
    — map never bumps, the var keeps training on the donor, params
    oracle-exact."""
    from paddle_tpu import observability as obs

    names, groups, servers, c = _mk_migration_fixture(monkeypatch)
    rounds = 6
    rb0 = obs.counter_value("ps.migrations", outcome="rollback") or 0
    try:
        # every install the donor attempts dies on the wire
        for s, _ in servers[:2]:
            monkeypatch.setattr(
                s, "_mig_client",
                lambda chain: (_ for _ in ()).throw(
                    OSError("recipient unreachable")))
        ws = {}
        for rnd in range(1, rounds + 1):
            for vi, n in enumerate(names):
                c.send_grad(n + "@GRAD", _grad(0, rnd) + vi,
                            round=rnd)
            c.send_barrier(round=rnd)
            ws = {n: c.get_param(n) for n in names}
            c.fetch_barrier()
            if rnd == 1:
                c.migrate(names[0], 1)
        assert c.map_version == 0 and not c.map_overrides
        assert (obs.counter_value("ps.migrations", outcome="rollback")
                or 0) > rb0
        assert names[0] in servers[0][1]  # donor still owns it
        for vi, n in enumerate(names):
            exp = {"w": np.zeros(4, "f4")}
            for rnd in range(1, rounds + 1):
                exp["w@GRAD"] = _grad(0, rnd) + vi
                _sgd_block(exp)
            assert ws[n].tobytes() == exp["w"].tobytes(), n
    finally:
        c.close()
        for s, _ in servers:
            s.stop()


# -- row-range live migration (ISSUE 18) -------------------------------------


class _SparseMigExec(MiniExec):
    def _write_var(self, scope, name, val):
        scope[name] = val  # keep SelectedRows grads un-coerced


def _sparse_sgd(scope):
    g = scope["emb@GRAD"]
    rows = np.asarray(g.rows(), dtype=np.int64)
    vals = np.asarray(g._value)
    emb = np.array(scope["emb"], copy=True)
    emb[rows] -= np.float32(0.1) * vals  # row-local, like pslib sgd
    scope["emb"] = emb


def _range_factory(gname):
    if gname.split("@", 1)[0] == "emb":
        return _sparse_sgd
    return _sgd_factory(gname)


def _mk_range_fixture(monkeypatch, height=16, width=4, lease_ms=400):
    """2 shards x (primary+backup): each shard holds its LOCAL slice
    of a height-``height`` sparse table ``emb`` (global rows sliced by
    ``row_range``) plus one dense var to drive the round barrier, the
    block factory armed so a recipient can rebuild the sparse
    optimize block for a range it adopts."""
    from paddle_tpu.distributed.ps_rpc import PSServer
    from paddle_tpu.distributed.ps_shard import (ShardedPSClient,
                                                 row_range)

    _fast_env(monkeypatch)
    # the donor's migration client inherits the replication deadline
    # captured at server construction: keep it tight so a blackholed
    # install fails fast instead of stalling the apply
    monkeypatch.setenv("PADDLE_PS_REPL_DEADLINE", "2")
    names = _shard_var_names(2)
    groups = [_eps(2), _eps(2)]
    servers = []
    for si, grp in enumerate(groups):
        lo, hi = row_range(si, height, 2)
        for ep in grp:
            scope = MiniScope()
            scope[names[si]] = np.zeros(4, dtype=np.float32)
            scope["emb"] = (np.arange(lo, hi, dtype=np.float32)
                            .reshape(-1, 1)
                            * np.ones((1, width), "f4"))
            g2b = {names[si] + "@GRAD": _sgd_factory(
                names[si] + "@GRAD"), "emb@GRAD": _sparse_sgd}
            s = PSServer(ep, _SparseMigExec(), scope, g2b, fanin=1,
                         endpoints=grp, lease_ms=lease_ms, shard=si,
                         block_factory=_range_factory)
            s.start_background()
            servers.append((s, scope))
    c = ShardedPSClient([",".join(g) for g in groups], trainer_id=0)
    return names, groups, servers, c


def _emb_oracle(height, width):
    return (np.arange(height, dtype=np.float32).reshape(-1, 1)
            * np.ones((1, width), "f4"))


def _push_round(c, oracle, rows, rnd, height, width):
    """Push one deterministic grad per row through the router AND
    fold it into the plain-numpy oracle (row-local sgd, lr 0.1)."""
    rows = np.asarray(rows, dtype=np.int64)
    vals = (np.float32(0.01) * np.float32(rnd)
            * (rows.astype(np.float32) + 1.0)[:, None]
            * np.ones((1, width), "f4"))
    c.push_sparse("emb@GRAD", rows, vals, height=height, param="emb")
    oracle[rows] = oracle[rows] - np.float32(0.1) * vals


def test_range_migration_end_to_end(monkeypatch):
    """Move GLOBAL rows [4, 8) of a sliced sparse table from shard 0
    to shard 1 mid-training: the map grows a per-range entry, moved
    rows re-base to recipient-LOCAL ids past its resident slice,
    pushes keep landing exactly once on both sides of the split, the
    donor's slice is zero-tombstoned after the replicated commit, and
    a fresh version-0 client self-repairs via wrong_shard."""
    from paddle_tpu.distributed.ps_shard import ShardedPSClient

    height, width = 16, 4
    names, groups, servers, c = _mk_range_fixture(
        monkeypatch, height=height, width=width)
    oracle = _emb_oracle(height, width)
    all_rows = np.arange(height, dtype=np.int64)
    rounds = 6
    try:
        for rnd in range(1, rounds + 1):
            _push_round(c, oracle, all_rows, rnd, height, width)
            for vi, n in enumerate(names):
                c.send_grad(n + "@GRAD", _grad(0, rnd) + vi,
                            round=rnd)
            c.send_barrier(round=rnd)
            c.fetch_barrier()
            if rnd == 2:
                r = c.migrate_range("emb", 4, 8, to_shard=1,
                                    height=height)
                assert r.get("pending"), r
        assert c.map_version >= 1
        assert c.map_ranges.get("emb") == [(4, 8, 1, 8)]
        got = c.pull_sparse("emb", all_rows, height=height)
        assert got.tobytes() == oracle.tobytes()
        # donor hard-committed: moved local rows [4, 8) are a zero
        # tombstone on the primary AND (via the dirty-dense stream)
        # its backup
        for srv, sc in servers[:2]:
            np.testing.assert_array_equal(
                np.asarray(sc["emb"])[4:8], np.zeros((4, width), "f4"))
        # recipient family grew to local height 12 on primary+backup
        assert np.asarray(servers[2][1]["emb"]).shape[0] == 12
        assert np.asarray(servers[3][1]["emb"]).shape[0] == 12
        # a fresh hash-routed client self-repairs via wrong_shard
        c2 = ShardedPSClient([",".join(g) for g in groups],
                             trainer_id=1)
        got2 = c2.pull_sparse("emb", [5, 4, 7, 1, 12], height=height)
        assert got2.tobytes() == oracle[[5, 4, 7, 1, 12]].tobytes()
        assert c2.map_version >= 1
        c2.close()
    finally:
        c.close()
        for s, _ in servers:
            s.stop()


def test_range_migration_partition_aborts_cleanly(monkeypatch):
    """An active ``partition:1:donor|recipient`` blackhole between the
    donor and recipient primaries while trainers keep pushing rows on
    both sides of the split point: bounded install retries, then
    ROLLBACK — no override anywhere, no orphan stage servable, zero
    lost or double-applied rows — and the same move succeeds once the
    partition heals."""
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed import fault

    height, width = 16, 4
    # a blackholed install stalls the donor's apply for the (tight)
    # replication deadline each round: keep the lease comfortably
    # above it so the test exercises the abort path, not elections
    names, groups, servers, c = _mk_range_fixture(
        monkeypatch, height=height, width=width, lease_ms=15000)
    oracle = _emb_oracle(height, width)
    all_rows = np.arange(height, dtype=np.int64)
    donor_rows = np.arange(8, dtype=np.int64)  # both sides of lo=4
    rb0 = obs.counter_value("ps.migrations", outcome="rollback") or 0
    prev_ident = fault.get_identity()
    try:
        # the partition rule severs traffic from THIS identity to the
        # named peer: stand in the donor primary's shoes
        fault.set_identity(groups[0][0])
        for rnd in (1, 2):
            _push_round(c, oracle, all_rows, rnd, height, width)
            for vi, n in enumerate(names):
                c.send_grad(n + "@GRAD", _grad(0, rnd) + vi,
                            round=rnd)
            c.send_barrier(round=rnd)
            c.fetch_barrier()
        monkeypatch.setenv("PADDLE_TPU_FAULTS", "partition:1:%s|%s"
                           % (groups[0][0], groups[1][0]))
        fault.reset_injector()
        # the migration client is created lazily at the first install
        # attempt — no in-client retries and no lease-wait loitering
        # on the recipient backup's not_primary hint: the round
        # barrier already re-drives the install
        monkeypatch.setenv("PADDLE_PS_REPL_RETRIES", "0")
        monkeypatch.setenv("PADDLE_PS_LEASE_WAIT_S", "1")
        monkeypatch.setenv("PADDLE_PS_FAILOVER_MAX", "1")
        r = c.migrate_range("emb", 4, 8, to_shard=1, height=height)
        assert r.get("pending"), r
        # shard-0-only rounds while the pair is severed (the full
        # barrier would cross the blackhole): every install attempt
        # dies on the wire, then the donor rolls back
        for rnd in range(3, 7):
            _push_round(c, oracle, donor_rows, rnd, height, width)
            c.send_grad(names[0] + "@GRAD", _grad(0, rnd), round=rnd)
            c.shards[0].barrier_prepare(round=rnd)
            c.shards[0].barrier_commit()
            c.shards[0].fetch_barrier()
        assert (obs.counter_value("ps.migrations", outcome="rollback")
                or 0) > rb0
        assert servers[0][0]._shard_map_version == 0
        assert not servers[0][0]._range_overrides
        assert "emb" not in servers[2][0]._staged_ranges
        assert c.map_version == 0 and not c.map_ranges
        monkeypatch.delenv("PADDLE_TPU_FAULTS")
        fault.reset_injector()
        # healed: rows all land exactly once, and the SAME move now
        # completes through the real protocol
        for rnd in (7, 8):
            _push_round(c, oracle, all_rows, rnd, height, width)
            for vi, n in enumerate(names):
                c.send_grad(n + "@GRAD", _grad(0, rnd) + vi,
                            round=rnd)
            c.send_barrier(round=rnd)
            c.fetch_barrier()
            if rnd == 7:
                assert c.migrate_range("emb", 4, 8, to_shard=1,
                                       height=height).get("pending")
        assert c.map_ranges.get("emb") == [(4, 8, 1, 8)]
        got = c.pull_sparse("emb", all_rows, height=height)
        assert got.tobytes() == oracle.tobytes()
    finally:
        monkeypatch.delenv("PADDLE_TPU_FAULTS", raising=False)
        fault.reset_injector()
        fault.set_identity(prev_ident)
        c.close()
        for s, _ in servers:
            s.stop()


# -- whole-job crash consistency (ISSUE 19) ----------------------------------


def _put_frame(store, rnd, mode="full", base=None, epoch=0):
    hdr = [{"name": "w", "dtype": "float32", "shape": [4]}]
    store.put_round(rnd, hdr, np.zeros(4, np.float32).tobytes(), {},
                    mode=mode, base_round=base, epoch=epoch)


def test_roundstore_torn_delta_drops_its_chain(tmp_path):
    """A frame is restorable only with its whole anchor->delta chain
    intact: tearing a mid-chain delta drops it AND every delta stacked
    on it, while the anchor (and the previous chain) stay loadable."""
    from paddle_tpu.checkpoint import CheckpointCorrupt, RoundStore

    store = RoundStore(str(tmp_path), shard=0)
    _put_frame(store, 1)
    _put_frame(store, 2, mode="delta", base=1)
    _put_frame(store, 3, mode="delta", base=2)
    assert store.restorable_rounds() == [1, 2, 3]
    blob = os.path.join(store.round_dir(2), "blob.bin")
    with open(blob, "r+b") as f:
        f.truncate(os.path.getsize(blob) // 2)
    fresh = RoundStore(str(tmp_path), shard=0)
    assert fresh.restorable_rounds() == [1], \
        "a torn delta must drop itself and everything chained past it"
    with pytest.raises(CheckpointCorrupt):
        fresh.load_round(3, lambda meta, raw: None)


def test_job_restore_round_is_the_common_cut(tmp_path):
    """Mixed per-shard progress (shard 0 durable through round 3,
    shard 1 only through round 2) restores the newest round present on
    EVERY shard — never a mixed cut."""
    from paddle_tpu.checkpoint import RoundStore, job_restore_round

    s0 = RoundStore(str(tmp_path), shard=0)
    s1 = RoundStore(str(tmp_path), shard=1)
    _put_frame(s0, 1)
    _put_frame(s0, 2, mode="delta", base=1)
    _put_frame(s0, 3, mode="delta", base=2)
    _put_frame(s1, 1)
    _put_frame(s1, 2, mode="delta", base=1)
    assert job_restore_round(str(tmp_path), 2) == 2
    # the laggard catches up: the cut advances with it
    _put_frame(s1, 3, mode="delta", base=2)
    assert job_restore_round(str(tmp_path), 2) == 3
    # tearing the newest frame on ONE shard pulls the job cut back
    blob = os.path.join(s1.round_dir(3), "blob.bin")
    with open(blob, "r+b") as f:
        f.truncate(os.path.getsize(blob) // 2)
    assert job_restore_round(str(tmp_path), 2) == 2


def test_job_restore_missing_shard_is_a_typed_error(tmp_path):
    """A restore that cannot see EVERY shard group must raise the
    typed error naming the missing shard — a partial or mixed restore
    never happens silently."""
    from paddle_tpu.checkpoint import (RestoreMissingShard, RoundStore,
                                       job_restore_round)

    _put_frame(RoundStore(str(tmp_path), shard=0), 1)
    with pytest.raises(RestoreMissingShard) as ei:
        job_restore_round(str(tmp_path), 2)
    assert ei.value.shard == 1
    assert "shard 1" in str(ei.value)
    # a shard dir whose every frame is torn is just as missing
    s1 = RoundStore(str(tmp_path), shard=1)
    _put_frame(s1, 1)
    blob = os.path.join(s1.round_dir(1), "blob.bin")
    with open(blob, "r+b") as f:
        f.truncate(2)
    fresh_err = pytest.raises(RestoreMissingShard,
                              job_restore_round, str(tmp_path), 2)
    assert fresh_err.value.shard == 1


def test_cold_restart_restores_bitwise_and_fences_dead_incarnation(
        monkeypatch, tmp_path):
    """The tentpole end to end in one process group: a sync primary
    with a durable dir persists every applied round; after a stop
    (standing in for SIGKILL — the frames are already on disk before
    any barrier ack) a fresh server booted with PADDLE_PS_RESTORE=1
    loads the newest round bit-for-bit, re-sends of already-applied
    rounds are dropped (exactly-once across the restart), training
    continues at cut+1, and a straggler from the dead incarnation's
    epoch is refused by the disk-restored fence."""
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed.ps_rpc import PSClient, _bare_rpc

    _fast_env(monkeypatch)
    durable = str(tmp_path)
    eps = _eps(1)
    s0, sc0 = _mk_ps(eps, 0, durable_dir=durable)
    try:
        c = PSClient(",".join(eps), trainer_id=0)
        for rnd in range(1, 5):
            c.send_grad("w@GRAD", _grad(0, rnd), round=rnd)
            c.send_barrier(round=rnd)
            c.fetch_barrier()
        w_dead = np.asarray(sc0["w"]).copy()
        c.close()
    finally:
        s0.stop()
    # whole-job loss: nothing survives but the durable dir
    monkeypatch.setenv("PADDLE_PS_RESTORE", "1")
    s1, sc1 = _mk_ps(eps, 0, durable_dir=durable)
    try:
        assert s1._restored_round == 4
        assert np.asarray(sc1["w"]).tobytes() == w_dead.tobytes(), \
            "cold restore must be bit-for-bit"
        c = PSClient(",".join(eps), trainer_id=0)
        c.seed_round(4)
        # a dead-incarnation re-send (round 4 already applied) must be
        # DROPPED, not folded into round 5
        stale0 = obs.counter_value("ps.stale_rounds") or 0
        c.send_grad("w@GRAD", _grad(0, 4), round=4)
        resp = c.barrier_prepare(round=4)
        assert resp.get("stale_round"), resp
        assert (obs.counter_value("ps.stale_rounds") or 0) > stale0
        assert np.asarray(sc1["w"]).tobytes() == w_dead.tobytes()
        # the job continues exactly-once at cut+1
        c.send_grad("w@GRAD", _grad(0, 5), round=5)
        c.send_barrier(round=5)
        c.fetch_barrier()
        oracle = {"w": np.zeros(4, "f4")}
        for rnd in range(1, 6):
            oracle["w@GRAD"] = _grad(0, rnd)
            _sgd_block(oracle)
        assert np.asarray(sc1["w"]).tobytes() == oracle["w"].tobytes()
        c.close()
        # a straggler still speaking the dead incarnation's epoch is
        # refused by the restored fence, loudly
        f0 = obs.counter_value("ps.fence_refused") or 0
        resp = _bare_rpc(eps[0], {"kind": "lease_renew", "epoch": 0,
                                  "frm": "ghost"})
        assert resp.get("fenced"), resp
        assert (obs.counter_value("ps.fence_refused") or 0) > f0
    finally:
        s1.stop()


def test_cold_restart_corrupt_newest_round_falls_back_one(
        monkeypatch, tmp_path):
    """A newest round frame torn by the crash (killed mid-rename or
    mid-write) silently falls the restore back to the previous
    complete round — bit-for-bit with what round 3 looked like — and
    the trainer-side manager clamps its own resume to that cut."""
    from paddle_tpu.checkpoint import CheckpointManager, RoundStore
    from paddle_tpu.distributed.ps_rpc import PSClient

    _fast_env(monkeypatch)
    durable = str(tmp_path / "ps")
    eps = _eps(1)
    s0, sc0 = _mk_ps(eps, 0, durable_dir=durable)
    w_at = {}
    try:
        c = PSClient(",".join(eps), trainer_id=0)
        for rnd in range(1, 5):
            c.send_grad("w@GRAD", _grad(0, rnd), round=rnd)
            c.send_barrier(round=rnd)
            c.fetch_barrier()
            w_at[rnd] = np.asarray(sc0["w"]).copy()
        c.close()
    finally:
        s0.stop()
    store = RoundStore(durable, shard=0)
    blob = os.path.join(store.round_dir(4), "blob.bin")
    with open(blob, "r+b") as f:
        f.truncate(os.path.getsize(blob) // 2)
    monkeypatch.setenv("PADDLE_PS_RESTORE", "1")
    s1, sc1 = _mk_ps(eps, 0, durable_dir=durable)
    try:
        assert s1._restored_round == 3
        assert np.asarray(sc1["w"]).tobytes() == w_at[3].tobytes()
    finally:
        s1.stop()
    # the trainer resumes AT OR BEFORE the fallen-back cut even though
    # its own newest checkpoint (step 4) outlived the servers' round 4
    ck = tmp_path / "trainer"
    mgr = CheckpointManager(str(ck))
    for step in (2, 3, 4):
        mgr.save(step, lambda d, s=step: open(
            os.path.join(d, "step.txt"), "w").write(str(s)))
    seen = []
    got = mgr.load_at_or_before(3, lambda d: seen.append(
        open(os.path.join(d, "step.txt")).read()))
    assert got == 3 and seen == ["3"]
    assert mgr.load_at_or_before(1, lambda d: None) is None


def test_async_oplog_replays_exactly_once_on_cold_restart(
        monkeypatch, tmp_path):
    """Async/geo mode: ops acked between synthetic-round frames live
    only in the durable op log; a cold restart replays exactly the
    tail past the restored frame's watermark — bit-for-bit with the
    uninterrupted sequential oracle, nothing lost, nothing doubled."""
    from paddle_tpu.distributed.ps_rpc import PSClient

    _fast_env(monkeypatch)
    durable = str(tmp_path)
    eps = _eps(1)
    s0, sc0 = _mk_ps(eps, 0, sync=False, durable_dir=durable)
    monkeypatch.setattr(s0, "_async_repl_every", 3)
    grads = [np.full(4, 0.01 * (i + 1), dtype=np.float32)
             for i in range(5)]
    try:
        c = PSClient(",".join(eps), trainer_id=0)
        for g in grads:
            c.send_grad("w@GRAD", g)
        w_dead = np.asarray(sc0["w"]).copy()
        c.close()
    finally:
        s0.stop()
    # ops 1-3 folded into the round-1 frame; 4 and 5 exist ONLY in
    # oplog.jsonl — the kill happens before their frame ships
    monkeypatch.setenv("PADDLE_PS_RESTORE", "1")
    s1, sc1 = _mk_ps(eps, 0, sync=False, durable_dir=durable)
    try:
        oracle = {"w": np.zeros(4, "f4")}
        for g in grads:
            oracle["w@GRAD"] = g
            _sgd_block(oracle)
        assert w_dead.tobytes() == oracle["w"].tobytes()
        assert np.asarray(sc1["w"]).tobytes() == oracle["w"].tobytes(), \
            "op-log replay lost or double-applied an acked async push"
    finally:
        s1.stop()
