"""Operator overloads for VarBase (eager math_op_patch).

Parity: /root/reference/python/paddle/fluid/dygraph/math_op_patch.py.
"""
from __future__ import annotations

import numpy as np

from .tracer import current_tracer
from .varbase import VarBase


def _trace(op_type, ins, attrs=None):
    return current_tracer().trace_op(op_type, ins, {}, attrs or {})


def _binary(op_type, x, y, reverse=False):
    if not isinstance(y, VarBase):
        if op_type == "elementwise_add":
            return _trace("scale", {"X": x}, {"scale": 1.0, "bias": float(y)})["Out"][0]
        if op_type == "elementwise_sub" and not reverse:
            return _trace("scale", {"X": x}, {"scale": 1.0, "bias": -float(y)})["Out"][0]
        if op_type == "elementwise_sub" and reverse:
            return _trace("scale", {"X": x}, {"scale": -1.0, "bias": float(y)})["Out"][0]
        if op_type == "elementwise_mul":
            return _trace("scale", {"X": x}, {"scale": float(y), "bias": 0.0})["Out"][0]
        if op_type == "elementwise_div" and not reverse:
            return _trace("scale", {"X": x}, {"scale": 1.0 / float(y), "bias": 0.0})["Out"][0]
        y = VarBase(np.full((1,), y, dtype=np.asarray(x.numpy()).dtype),
                    stop_gradient=True)
    a, b = (y, x) if reverse else (x, y)
    return _trace(op_type, {"X": a, "Y": b}, {"axis": -1})["Out"][0]


def monkey_patch_varbase():
    def _make(op_type, reverse=False):
        def impl(self, other):
            return _binary(op_type, self, other, reverse)

        return impl

    VarBase.__add__ = _make("elementwise_add")
    VarBase.__radd__ = _make("elementwise_add")
    VarBase.__sub__ = _make("elementwise_sub")
    VarBase.__rsub__ = _make("elementwise_sub", reverse=True)
    VarBase.__mul__ = _make("elementwise_mul")
    VarBase.__rmul__ = _make("elementwise_mul")
    VarBase.__truediv__ = _make("elementwise_div")
    VarBase.__rtruediv__ = _make("elementwise_div", reverse=True)
    VarBase.__pow__ = _make("elementwise_pow")
    VarBase.__mod__ = _make("elementwise_mod")
    VarBase.__neg__ = lambda self: _trace(
        "scale", {"X": self}, {"scale": -1.0, "bias": 0.0})["Out"][0]
    VarBase.__matmul__ = lambda self, other: _trace(
        "matmul", {"X": self, "Y": other},
        {"transpose_X": False, "transpose_Y": False, "alpha": 1.0})["Out"][0]


monkey_patch_varbase()
