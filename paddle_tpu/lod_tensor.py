"""LoDTensor construction helpers.

Parity: /root/reference/python/paddle/fluid/lod_tensor.py
(create_lod_tensor :24, create_random_int_lodtensor :97). The recursive
sequence-length convention matches the reference: lengths per level,
converted to offset LoD on the tensor.
"""
from __future__ import annotations

import numpy as np

from .core.tensor import LoDTensor

__all__ = ["create_lod_tensor", "create_random_int_lodtensor"]


def _lengths_to_offsets(recursive_seq_lens):
    lods = []
    for lengths in recursive_seq_lens:
        offs = [0]
        for n in lengths:
            offs.append(offs[-1] + int(n))
        lods.append(offs)
    return lods


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Build a LoDTensor from a numpy array / list / LoDTensor plus
    per-level sequence LENGTHS (reference lod_tensor.py:24)."""
    if isinstance(data, LoDTensor):
        return create_lod_tensor(np.asarray(data.array),
                                 recursive_seq_lens, place)
    if isinstance(data, list):
        # list of per-sequence rows: lengths must match
        flat = np.concatenate([np.asarray(d).reshape(-1, 1)
                               for d in data], axis=0)
        lens = [len(np.asarray(d).reshape(-1)) for d in data]
        if recursive_seq_lens and \
                list(recursive_seq_lens[-1]) != lens:
            raise ValueError(
                "recursive_seq_lens %s does not match data lengths %s"
                % (recursive_seq_lens, lens))
        data = flat
    arr = np.asarray(data)
    lods = _lengths_to_offsets(recursive_seq_lens)
    if lods and lods[-1][-1] != arr.shape[0]:
        raise ValueError(
            "last-level offsets end at %d but data has %d rows"
            % (lods[-1][-1], arr.shape[0]))
    t = LoDTensor(arr)
    t.set_lod(lods)
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1):
    """Random int64 LoDTensor whose last level has the given lengths
    (reference lod_tensor.py:97) — the word-id test-data helper."""
    total = int(sum(recursive_seq_lens[-1]))
    shape = [total] + list(base_shape)
    data = np.random.randint(low, high + 1, size=shape).astype("int64")
    return create_lod_tensor(data, recursive_seq_lens, place)
