"""Paged decode-step attention: one query token per sequence, keys and
values read straight out of the serving tier's paged KV-cache arena.

The continuous-batching decode engine (``serving/decode``) stores every
sequence's KV history in fixed-size blocks scattered over ONE
preallocated arena; a per-sequence block table maps logical token
positions to arena blocks. A decode step then needs attention of shape
``q:[B, H, D] x cache:[ragged lengths]`` — the classic "paged
attention" kernel. Materializing each sequence's cache densely per step
(gather + concatenate) is exactly the copy this layout exists to avoid,
so the kernel reads the arena THROUGH the block table:

- **pallas TPU path** — grid ``(B, max_blocks)``: the block table rides
  in as a scalar-prefetch operand (``PrefetchScalarGridSpec``), so each
  grid step's index map picks the NEXT arena block for this sequence
  and pallas streams exactly that ``[block_tokens, H, D]`` tile
  HBM->VMEM; a running-softmax scratch (m, l, acc — the flash
  accumulation, float32 regardless of storage dtype) persists across
  the sequentially-iterated block axis. Padded table entries re-fetch
  block 0 and are masked by the per-sequence length, so the ragged
  batch pads to a rectangle without touching ragged memory.
- **dense fallback** (CPU/CI and any host without pallas): identical
  math in numpy over the same arena + block table. The serving smoke
  runs on CPU hosts, so this path IS the production path there; the
  pallas path takes over on TPU where the arena actually lives in HBM.

Quantized arenas (the EQuARX-shaped KV trick: shared-scale int8 codes,
``serving/decode/kvcache.py``) pass their per-(block, head) scales;
dequantization happens tile-local in the kernel — codes travel
HBM->VMEM at 1/4 the f32 width, which is the whole point of quantizing
the cache. bf16 arenas arrive as uint16 bit patterns and are widened
the same way.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np

__all__ = ["paged_decode_attention", "paged_attention_reference"]

NEG_INF = -1e30


def _widen(arr, scales, block_ids):
    """Dequantize one gathered [T, H, D] slab to float32.

    ``scales`` is None for f32 arenas, the per-(block, head) scale
    array for int8 codes, or the string ``"bf16"`` for uint16 bit
    patterns (value = bits << 16 reinterpreted as float32)."""
    if scales is None:
        return arr.astype(np.float32)
    if isinstance(scales, str) and scales == "bf16":
        return (arr.astype(np.uint32) << 16).view(np.float32)
    # int8 codes: scale indexed per source block, broadcast over the
    # block's tokens and the head dim
    s = scales[block_ids]                       # [T, H]
    return arr.astype(np.float32) * s[:, :, None]


def paged_attention_reference(q, k_arena, v_arena, block_tables,
                              seq_lens, *, block_tokens: int,
                              scale: Optional[float] = None,
                              k_scales=None, v_scales=None):
    """Dense reference: gather each sequence's blocks, run softmax
    attention, return ``[B, H, D]`` float32. Zero-length rows (padded
    batch slots) return zeros.

    ``k_scales``/``v_scales``: per-(block, head) float32 scales for
    int8 arenas, or the string ``"bf16"`` for uint16 bf16 arenas, or
    None for float32 storage. Shapes: q ``[B, H, D]``, arenas
    ``[num_blocks, block_tokens, H, D]``, block_tables
    ``[B, max_blocks]`` int (-1 padded), seq_lens ``[B]`` int.
    """
    q = np.asarray(q, np.float32)
    B, H, D = q.shape
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    out = np.zeros((B, H, D), np.float32)
    block_tables = np.asarray(block_tables)
    seq_lens = np.asarray(seq_lens)
    for b in range(B):
        n = int(seq_lens[b])
        if n <= 0:
            continue
        nblk = -(-n // block_tokens)
        ids = block_tables[b, :nblk]
        # token t lives at (ids[t // bt], t % bt)
        tok_blocks = np.repeat(ids, block_tokens)[:n]
        k = _widen(k_arena[ids].reshape(-1, H, D)[:n], k_scales,
                   tok_blocks)
        v = _widen(v_arena[ids].reshape(-1, H, D)[:n], v_scales,
                   tok_blocks)
        s = np.einsum("hd,thd->ht", q[b], k) * scale      # [H, T]
        s -= s.max(axis=1, keepdims=True)
        p = np.exp(s)
        p /= p.sum(axis=1, keepdims=True)
        out[b] = np.einsum("ht,thd->hd", p, v)
    return out


# ---------------------------------------------------------------------------
# pallas TPU kernel
# ---------------------------------------------------------------------------


def _paged_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, block_tokens, scale,
                  n_blocks):
    """One (sequence, cache-block) grid step: flash accumulation over
    this block's keys/values. The index maps already routed the RIGHT
    arena block into ``k_ref``/``v_ref`` via the prefetched table."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    seq_len = lens_ref[pl.program_id(0)]
    base = j * block_tokens
    valid = base < seq_len

    @pl.when(valid)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)                 # [H, D]
        k = k_ref[0].astype(jnp.float32)                 # [T, H, D]
        v = v_ref[0].astype(jnp.float32)
        s = jnp.einsum("hd,thd->ht", q, k) * scale       # [H, T]
        pos = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < seq_len, s, NEG_INF)
        m_prev = m_ref[...]                              # [H, 1]
        m_cur = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)                           # [H, T]
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + \
            jnp.einsum("ht,thd->hd", p, v)
        m_ref[...] = m_cur

    @pl.when(j == n_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_pallas(q, k_arena, v_arena, block_tables, seq_lens, *,
                  block_tokens, scale, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, D = q.shape
    max_blocks = block_tables.shape[1]
    # padded (-1) table entries re-fetch block 0; the length mask in
    # the kernel hides their tokens
    tables = jnp.maximum(jnp.asarray(block_tables, jnp.int32), 0)
    lens = jnp.asarray(seq_lens, jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_blocks),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, j, t, sl: (b, 0, 0)),
            pl.BlockSpec((1, block_tokens, H, D),
                         lambda b, j, t, sl: (t[b, j], 0, 0, 0)),
            pl.BlockSpec((1, block_tokens, H, D),
                         lambda b, j, t, sl: (t[b, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, j, t, sl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_kernel, block_tokens=block_tokens,
                               scale=scale, n_blocks=max_blocks)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), jnp.float32),
        interpret=interpret,
    )(tables, lens, jnp.asarray(q, jnp.float32),
      jnp.asarray(k_arena), jnp.asarray(v_arena))
    return np.asarray(out)


def paged_decode_attention(q, k_arena, v_arena, block_tables, seq_lens,
                           *, block_tokens: int,
                           scale: Optional[float] = None,
                           k_scales=None, v_scales=None,
                           backend: Optional[str] = None):
    """Decode-step attention over a paged KV cache.

    ``backend``: ``None`` picks pallas on TPU and the dense path
    elsewhere; ``"dense"`` forces the reference; ``"pallas"`` /
    ``"pallas_interpret"`` force the kernel (tests run interpret-mode
    parity on CPU). Quantized arenas (int8 codes / bf16 bit patterns)
    always take the dense path off-TPU — on-TPU they are widened
    tile-local, off-TPU there is no bandwidth to save.
    """
    if scale is None:
        scale = 1.0 / float(np.sqrt(np.asarray(q).shape[-1]))
    quantized = k_scales is not None or v_scales is not None
    if backend is None:
        use_pallas = False
        if not quantized:
            try:
                import jax
                use_pallas = jax.default_backend() == "tpu"
            except Exception:  # noqa: BLE001 — no jax, dense it is
                use_pallas = False
        backend = "pallas" if use_pallas else "dense"
    if backend == "dense":
        return paged_attention_reference(
            q, k_arena, v_arena, block_tables, seq_lens,
            block_tokens=block_tokens, scale=scale,
            k_scales=k_scales, v_scales=v_scales)
    if quantized:
        raise ValueError("pallas paged attention path takes f32 arenas; "
                         "dequantize via backend='dense' off-TPU")
    return _paged_pallas(
        np.asarray(q, np.float32), np.asarray(k_arena, np.float32),
        np.asarray(v_arena, np.float32), block_tables, seq_lens,
        block_tokens=block_tokens, scale=scale,
        interpret=(backend == "pallas_interpret"))
