"""Stdlib HTTP front end for a ServingEngine.

``ThreadingHTTPServer`` — one thread per connection — is exactly the
right shape here: client threads block on their request future while
the engine batches across them, so concurrency at the HTTP layer IS the
batch-formation opportunity. No framework dependency.

Endpoints:

- ``POST /predict`` — body ``{"inputs": {name: nested-list},
  "deadline_ms": optional, "cost_class": optional}``; arrays carry the
  leading batch axis; an ``X-Request-Id`` header makes the request
  idempotent (a hedge/retry duplicate joins the original execution).
  Replies ``{"outputs": {name: nested-list}, "latency_ms": float}``.
  Typed failures map onto status codes AND carry a machine-readable
  ``type`` field: 503 (``ServerOverloaded`` / ``RequestShed`` with
  ``Retry-After``, ``EngineStopped``), 504 (``DeadlineExpired`` — the
  deadline passed while queued), 400 (malformed), 500
  (``BatchExecutionError`` — the model failed on that batch; the
  engine stays healthy).
- ``POST /generate`` — streaming decode (an engine exposing
  ``generate()``, i.e. a ``DecodeEngine`` or a fleet front of them):
  chunked ndjson token events terminated by one finish event; see
  ``_do_generate``. 501 on a one-shot engine.
- ``GET /healthz`` — machine-readable lifecycle: 200 with
  ``{"status": "serving"}`` only while the engine accepts work, 503
  with the actual state (``starting | warming | draining | stopped``)
  otherwise — a fleet router stops routing at ``draining``, not at
  connection refusal. Engines with ``health_doc()`` enrich the body:
  ``engine_kind`` (``oneshot | decode``) plus, on decode replicas,
  the KV occupancy a router places streams by. The body also names
  this process's metrics-dump path (``metrics_dump``) so an operator
  probing a replica knows where its telemetry lands.
- ``GET /metrics`` — the FULL observability registry via
  ``observability.dump_prometheus()`` (one code path with every other
  exporter: serving.* plus every runtime family, histogram quantile
  / _sum / _count series included).

Trace propagation: ``POST /predict`` honors an ``X-Trace-Id`` (+
optional ``X-Parent-Span``) request header — the request's engine
spans land under the caller's trace — and always echoes the trace id
back in the response's ``X-Trace-Id`` header when spans are armed.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

import numpy as np

from .. import observability as _obs
from ..observability import distributed as _dtrace
from .engine import (BatchExecutionError, DeadlineExpired, EngineStopped,
                     RequestTooLarge, ServerOverloaded, ServingEngine)

__all__ = ["ServingHTTPServer", "start_http_server", "serve"]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # -- plumbing ----------------------------------------------------------

    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        pass  # per-request stderr lines are noise; /metrics is the log

    def _reply(self, code: int, body: bytes, ctype: str,
               extra_headers: Tuple = ()) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, code: int, payload: dict,
                    extra_headers: Tuple = ()) -> None:
        self._reply(code, json.dumps(payload).encode(),
                    "application/json", extra_headers)

    # -- routes ------------------------------------------------------------

    def do_GET(self):  # noqa: N802 — stdlib naming
        engine = self.server.engine
        if self.path == "/healthz":
            health = engine.health()
            # engines that implement health_doc() (ServingEngine:
            # engine_kind=oneshot; DecodeEngine: engine_kind=decode +
            # KV occupancy) enrich the body; anything else — e.g. a
            # FleetRouter front — keeps the bare status contract
            doc_fn = getattr(engine, "health_doc", None)
            doc = doc_fn() if callable(doc_fn) else {"status": health}
            doc["metrics_dump"] = _dtrace.dump_path()
            if health == "serving":
                self._reply_json(200, _json_safe(doc))
            else:
                # starting/warming: not ready yet; "draining": stop()
                # flipped readiness but in-flight requests are still
                # finishing — the supervisor must stop routing now and
                # NOT kill the process yet
                self._reply_json(503, _json_safe(doc))
        elif self.path == "/metrics":
            self._reply(200, _obs.dump_prometheus().encode(),
                        "text/plain; version=0.0.4")
        elif self.path == "/stats":
            self._reply_json(200, _json_safe(engine.stats()))
        else:
            self._reply_json(404, {"error": "no route %s" % self.path})

    def do_POST(self):  # noqa: N802
        if self.path == "/generate":
            self._do_generate()
            return
        if self.path != "/predict":
            self._reply_json(404, {"error": "no route %s" % self.path})
            return
        engine: ServingEngine = self.server.engine
        t0 = time.monotonic()
        req_ctx = None
        try:
            length = int(self.headers.get("Content-Length") or 0)
            req = json.loads(self.rfile.read(length) or b"{}")
            inputs = req.get("inputs")
            if not isinstance(inputs, dict) or not inputs:
                raise ValueError('body needs {"inputs": {name: array}}')
            deadline_ms = req.get("deadline_ms")
            if deadline_ms is not None and not isinstance(
                    deadline_ms, (int, float)):
                raise ValueError("deadline_ms must be a number, got %r"
                                 % (deadline_ms,))
            cost_class = req.get("cost_class")
            if cost_class is not None and not isinstance(cost_class, str):
                raise ValueError("cost_class must be a string, got %r"
                                 % (cost_class,))
            request_id = self.headers.get("X-Request-Id") or None
            feed = {str(n): np.asarray(v) for n, v in inputs.items()}
            # a caller-supplied X-Trace-Id joins this request to the
            # caller's trace; without one each request is its own
            # trace. submit() captures the context, so the worker-side
            # dispatch span lands under it too.
            with _dtrace.child_span(
                    "serving.request", cat="serving",
                    trace_id=self.headers.get("X-Trace-Id") or None,
                    parent_span=self.headers.get("X-Parent-Span")
                    or None) as ctx:
                req_ctx = ctx
                outputs = engine.predict(feed, deadline_ms=deadline_ms,
                                         request_id=request_id,
                                         cost_class=cost_class)
        except ServerOverloaded as e:
            # RequestShed is a ServerOverloaded subtype: same 503 +
            # Retry-After back-off, but the typed name tells the
            # caller its COST CLASS was shed (a cheaper class may
            # still be admitted) rather than the hard queue bound hit
            self._reply_json(503, {"error": str(e),
                                   "type": type(e).__name__},
                             (("Retry-After", "1"),) + self._echo(req_ctx))
        except EngineStopped as e:
            self._reply_json(503, {"error": str(e),
                                   "type": "EngineStopped"},
                             self._echo(req_ctx))
        except DeadlineExpired as e:
            # typed 504: the deadline expired while the request was
            # QUEUED (it never reached the predictor) — the caller's
            # retry/hedge budget accounting needs to distinguish this
            # from a transport loss
            self._reply_json(504, {"error": str(e),
                                   "type": "DeadlineExpired"},
                             self._echo(req_ctx))
        except BatchExecutionError as e:
            # the MODEL failed on this batch: the engine is still
            # healthy (don't drain), the CLIENT isn't at fault (not a
            # 4xx) — a plain 500 with the typed name
            self._reply_json(500, {"error": str(e),
                                   "type": "BatchExecutionError"},
                             self._echo(req_ctx))
        except (ValueError, RequestTooLarge, json.JSONDecodeError) as e:
            self._reply_json(400, {"error": str(e)}, self._echo(req_ctx))
        except Exception as e:  # noqa: BLE001 — the model failed
            self._reply_json(500, {"error": "%s: %s"
                                   % (type(e).__name__, e)},
                             self._echo(req_ctx))
        else:
            self._reply_json(200, {
                "outputs": {n: np.asarray(v).tolist()
                            for n, v in outputs.items()},
                "latency_ms": (time.monotonic() - t0) * 1e3,
            }, self._echo(req_ctx))

    @staticmethod
    def _echo(req_ctx) -> Tuple:
        """The X-Trace-Id echo, on EVERY /predict reply — a failed
        request is the one the caller most needs to correlate with its
        distributed trace."""
        return (("X-Trace-Id", req_ctx.trace_id),) if req_ctx else ()

    # -- streaming decode ---------------------------------------------------

    def _do_generate(self):
        """``POST /generate``: chunked ndjson token stream.

        Body: ``{"prompt": [ids], "max_tokens": n, "cost_class": c,
        "deadline_ms": d, "resume_from": i}``; ``X-Request-Id`` makes
        the stream idempotent (a hedge/failover duplicate replays or
        attaches, and ``resume_from`` suppresses already-delivered
        token indices — the fleet's exactly-once resume contract).

        Reply: 200 + ``Transfer-Encoding: chunked``, one JSON object
        per line — ``{"type": "token", "index": i, "token": t}``
        events, then exactly one terminal
        ``{"type": "finish", "reason": ...}``. Admission failures
        reject BEFORE the stream starts, with the same typed status
        mapping as /predict; once streaming, failures arrive in-band
        as the finish event (the status line is already gone)."""
        engine = self.server.engine
        gen = getattr(engine, "generate", None)
        if gen is None:
            self._reply_json(
                501, {"error": "engine %s does not stream"
                      % type(engine).__name__,
                      "type": "NotStreaming"})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            req = json.loads(self.rfile.read(length) or b"{}")
            prompt = req.get("prompt")
            if not isinstance(prompt, list) or not prompt or \
                    not all(isinstance(t, int) for t in prompt):
                raise ValueError(
                    'body needs {"prompt": [token ids]}')
            deadline_ms = req.get("deadline_ms")
            if deadline_ms is not None and not isinstance(
                    deadline_ms, (int, float)):
                raise ValueError("deadline_ms must be a number, got %r"
                                 % (deadline_ms,))
            stream = gen(
                prompt,
                max_tokens=req.get("max_tokens"),
                request_id=self.headers.get("X-Request-Id") or None,
                cost_class=req.get("cost_class") or "high",
                deadline_s=(deadline_ms / 1e3
                            if deadline_ms is not None else None),
                resume_from=int(req.get("resume_from") or 0))
        except ServerOverloaded as e:
            self._reply_json(503, {"error": str(e),
                                   "type": type(e).__name__},
                             (("Retry-After", "1"),))
            return
        except EngineStopped as e:
            self._reply_json(503, {"error": str(e),
                                   "type": "EngineStopped"})
            return
        except (ValueError, RequestTooLarge,
                json.JSONDecodeError) as e:
            self._reply_json(400, {"error": str(e),
                                   "type": type(e).__name__})
            return
        except Exception as e:  # noqa: BLE001 — engine-side failure
            self._reply_json(500, {"error": "%s: %s"
                                   % (type(e).__name__, e)})
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            for ev in stream:
                self._write_chunk(json.dumps(ev).encode() + b"\n")
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError,
                ConnectionAbortedError):
            # client went away mid-stream (hedge loser, dead caller):
            # stop generating for it
            cancel = getattr(stream, "cancel", None)
            if callable(cancel):
                cancel()
            raise

    def _write_chunk(self, data: bytes) -> None:
        self.wfile.write(b"%x\r\n" % len(data))
        self.wfile.write(data)
        self.wfile.write(b"\r\n")
        self.wfile.flush()


def _json_safe(obj):
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (np.integer, np.floating)):
        return obj.item()
    return obj


class ServingHTTPServer(ThreadingHTTPServer):
    """HTTP front of one ServingEngine (or a FleetRouter — anything
    with the ``predict``/``health``/``stats`` surface). ``port=0``
    binds an ephemeral port (tests); ``server.server_address`` reports
    the real one."""

    daemon_threads = True

    def __init__(self, engine: ServingEngine, host: str = "127.0.0.1",
                 port: int = 8080):
        self.engine = engine
        super().__init__((host, port), _Handler)

    def handle_error(self, request, client_address):
        # a client hanging up mid-reply is NORMAL under a fleet: the
        # hedge winner cancels the loser by closing its socket, and a
        # deadline-expired caller walks away — neither deserves a
        # stack trace in the replica log
        import sys as _sys

        exc = _sys.exc_info()[1]
        if isinstance(exc, (ConnectionResetError, BrokenPipeError,
                            ConnectionAbortedError)):
            return
        super().handle_error(request, client_address)


def start_http_server(engine: ServingEngine, host: str = "127.0.0.1",
                      port: int = 0) -> Tuple[ServingHTTPServer,
                                              threading.Thread]:
    """Non-blocking: serve on a background thread (tests, embedding)."""
    server = ServingHTTPServer(engine, host, port)
    thread = threading.Thread(target=server.serve_forever,
                              name="serving-http", daemon=True)
    thread.start()
    return server, thread


def serve(engine: ServingEngine, host: str = "0.0.0.0",
          port: int = 8080) -> None:
    """Blocking entry point: start the engine, serve until interrupted,
    then drain. The accept loop runs on a background thread so that
    DURING the drain the server still answers — /healthz returns 503
    (the load-balancer back-off signal) while queued work finishes —
    and only then is the listening socket closed."""
    engine.start()
    server = ServingHTTPServer(engine, host, port)
    thread = threading.Thread(target=server.serve_forever,
                              name="serving-http", daemon=True)
    thread.start()
    try:
        while thread.is_alive():
            thread.join(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        engine.stop()       # drain: probes see 503, submits refused
        server.shutdown()
        server.server_close()
