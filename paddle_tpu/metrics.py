"""Python-side metrics.

Parity: /root/reference/python/paddle/fluid/metrics.py (MetricBase,
CompositeMetric, Precision, Recall, Accuracy, ChunkEvaluator, EditDistance,
Auc, DetectionMAP subset).
"""
from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Precision", "Recall", "Accuracy",
           "Auc"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if k.startswith("_"):
                continue
            if isinstance(v, (int, float)):
                setattr(self, k, 0 if isinstance(v, int) else 0.0)
            elif isinstance(v, np.ndarray):
                setattr(self, k, np.zeros_like(v))

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        ap = self.tp + self.fn
        return float(self.tp) / ap if ap else 0.0


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        return self.value / self.weight if self.weight else 0.0


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.stat_pos = np.zeros(num_thresholds + 1, dtype=np.int64)
        self.stat_neg = np.zeros(num_thresholds + 1, dtype=np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        bucket = np.clip((pos_prob * self._num_thresholds).astype(np.int64),
                         0, self._num_thresholds)
        for b, l in zip(bucket, labels):
            if l:
                self.stat_pos[b] += 1
            else:
                self.stat_neg[b] += 1

    def eval(self):
        tot_pos = self.stat_pos.sum()
        tot_neg = self.stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # accumulate from the highest threshold downward
        tp = np.cumsum(self.stat_pos[::-1])
        fp = np.cumsum(self.stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))
