"""CV detection ops (wave 2+).

Parity target: /root/reference/paddle/fluid/operators/detection/ (~16k
LoC: prior_box, multiclass_nms, yolo_box, roi_align, generate_proposals,
...). First wave: the dense, shape-static ones; NMS-style value-dependent
shapes become host ops when added.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import In, Out, register_op


@register_op(
    "box_coder",
    inputs=[In("PriorBox", no_grad=True), In("PriorBoxVar", dispensable=True,
            no_grad=True), In("TargetBox")],
    outputs=[Out("OutputBox")],
    attrs={"code_type": "encode_center_size", "box_normalized": True, "axis": 0,
           "variance": []},
)
def _box_coder(ins, attrs):
    prior = ins["PriorBox"]
    target = ins["TargetBox"]
    norm = attrs.get("box_normalized", True)
    pw = prior[:, 2] - prior[:, 0] + (0.0 if norm else 1.0)
    ph = prior[:, 3] - prior[:, 1] + (0.0 if norm else 1.0)
    px = prior[:, 0] + pw * 0.5
    py = prior[:, 1] + ph * 0.5
    if attrs.get("code_type", "encode_center_size") == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + (0.0 if norm else 1.0)
        th = target[:, 3] - target[:, 1] + (0.0 if norm else 1.0)
        tx = target[:, 0] + tw * 0.5
        ty = target[:, 1] + th * 0.5
        out = jnp.stack(
            [(tx[:, None] - px[None, :]) / pw[None, :],
             (ty[:, None] - py[None, :]) / ph[None, :],
             jnp.log(tw[:, None] / pw[None, :]),
             jnp.log(th[:, None] / ph[None, :])],
            axis=-1,
        )
        var = ins.get("PriorBoxVar")
        if var is not None:
            out = out / var[None, :, :]
        elif attrs.get("variance"):
            out = out / jnp.asarray(attrs["variance"]).reshape(1, 1, 4)
        return {"OutputBox": out}
    raise NotImplementedError("decode_center_size arrives with wave 2")
