"""Mesh data-parallel execution engine.

TPU-native replacement for ParallelExecutor
(/root/reference/paddle/fluid/framework/parallel_executor.cc:443 — graph
cloned per device, AllReduceOpHandles over NCCL, SSA thread schedulers):
here the whole-program trace is wrapped in ONE shard_map over a 1-D mesh:

- feeds are batch-sharded (in_spec P('dp')) — the scatter the reference
  does by slicing feed tensors per device (executor.py _split_data);
- params/optimizer state are replicated (in_spec P()); the collective
  transpiler has inserted c_allreduce_sum on grads + 1/n loss scaling, so
  updates stay bitwise-replicated — no BCastParamsToDevices needed;
- `ring_id` attrs resolve to the mesh axis via ring_axis_guard, lowering
  to lax.psum on ICI (replacing NCCLCommContext rings);
- fetches are all-gathered to every shard and returned stacked [n, ...],
  matching ParallelExecutor's merged fetch semantics.

XLA compiles the one program per-shard and inserts the collectives —
there is no SSA scheduler to build, which is the point.
"""
from __future__ import annotations

from typing import Dict, Sequence, Set, Tuple

import numpy as np

from ..core.compiler_engine import _analyze, _program_version, _trace_block
from ..core.registry import BOUND_OUTPUTS_ATTR
from ..core.scope import Scope
from ..core.tensor import LoDTensor
from ..ops.collective_ops import mesh_axes_guard, ring_axis_guard
from .mesh_utils import (default_mesh, mesh_key as _mesh_key,
                         shard_map_compat as _shard_map)
from .transpiler import insert_allreduce_ops

_dp_cache: Dict = {}

# local sync-round counter: dp ranks advance in lockstep (the
# allreduce IS the barrier), so every rank's Nth mesh step is the same
# logical round — the basis for joining one round's spans to the job
# trace without any rank-to-rank message (distributed.fleet_round_args)
_sync_round = 0


def _var_nbytes(block, state: Dict, name: str) -> Tuple[int, int]:
    """(bytes, itemsize) of a var via the shared size resolver in
    parallel.collectives (block shape, else live value, else the
    replicated param a grad mirrors); unknown shapes count as 0 bytes
    rather than guessing."""
    from .collectives import _numel_and_dtype

    n, dtype = _numel_and_dtype(block, state, name)
    try:
        item = np.dtype(dtype or "float32").itemsize
    except TypeError:
        item = 4
    return (0 if n is None else n * item), item


# collective op type -> traffic kind label; substring match for the
# c_allreduce_{sum,max,...} family
_COLLECTIVE_KINDS = (
    ("bucket_allreduce", "allreduce"), ("sharded_update", None),
    ("allreduce", "allreduce"), ("allgather", "allgather"),
    ("reducescatter", "reducescatter"), ("broadcast", "broadcast"),
)


def _quant_wire_itemsize(attrs, exact_itemsize: int,
                         native: bool = False) -> int:
    """Per-element payload width of a (possibly quantized) collective:
    by default what the emulated lowering actually moves (int8 codes
    psum in int32 — see QUANT_PSUM_ITEMSIZE); ``native=True`` gives
    the width a native quantized collective would move instead."""
    from ..ops.collective_ops import (QUANT_PSUM_ITEMSIZE,
                                      QUANT_WIRE_ITEMSIZE)

    table = QUANT_WIRE_ITEMSIZE if native else QUANT_PSUM_ITEMSIZE
    wire = table.get(attrs.get("quant", "none"))
    return exact_itemsize if wire is None else wire


def _estimate_collective_bytes(program, state: Dict,
                               native_wire: bool = False) -> Dict:
    """Per-kind collective traffic estimate over the transpiled
    program's c_* collectives — the EQuARX-style comms counter a
    collective-compression PR needs as its before/after.

    Returns ``{"ops": {kind: n}, "bytes": {kind: wire_bytes},
    "ops_total": N, "bytes_total": B, "bytes_exact": E}`` where *wire*
    bytes are what the EXECUTED program moves (bf16 payloads count 2
    bytes/element, but int8 codes psum in int32 so they count 4) and
    *exact* bytes are the same traffic uncompressed. With
    ``native_wire=True`` quantized payloads are charged at the width a
    native quantized collective would move (int8 = 1 byte/element) —
    ``E - B`` under that mode is the PROJECTED bytes-saved figure the
    multichip bench records."""
    block = program.global_block()
    ops_by_kind: Dict[str, int] = {}
    bytes_by_kind: Dict[str, int] = {}
    exact_total = 0

    def _add(kind, n_ops, wire_bytes, exact_bytes):
        nonlocal exact_total
        ops_by_kind[kind] = ops_by_kind.get(kind, 0) + n_ops
        bytes_by_kind[kind] = bytes_by_kind.get(kind, 0) + wire_bytes
        exact_total += exact_bytes

    for op in block.ops:
        if not op.type.startswith("c_"):
            continue
        if op.type.endswith("_await"):
            # the await half of an async pair moves no wire bytes —
            # its start op already carried the payload
            continue
        kind = next((k for sub, k in _COLLECTIVE_KINDS if sub in op.type),
                    "skip")
        if kind == "skip":
            continue
        if op.type == "c_sharded_update":
            # one flat (optionally quantized) grad psum + one allgather
            # of updated param shards, both over the padded flat size
            padded = int(op.attrs.get("padded_size", 0))
            pname = op.input("Param")[0] if op.input("Param") else None
            _, item = _var_nbytes(block, state, pname) if pname else (0, 4)
            wire_item = _quant_wire_itemsize(op.attrs, item, native_wire)
            _add("allreduce", 1, padded * wire_item, padded * item)
            _add("allgather", 1, padded * item, padded * item)
            continue
        if op.type.startswith("c_bucket_allreduce"):
            # payload = the X members only (an error-feedback Residual
            # is device-local state, not wire traffic)
            names = [n for n in op.input("X") if n]
        else:
            names = [n for n in op.input_arg_names if n]
        exact = sum(_var_nbytes(block, state, n)[0] for n in names)
        if op.type.startswith("c_bucket_allreduce"):
            item = 4
            for n in names:
                item = _var_nbytes(block, state, n)[1]
                break
            wire_item = _quant_wire_itemsize(op.attrs, item, native_wire)
            _add(kind, 1, int(exact * wire_item / item), exact)
        else:
            _add(kind, 1, exact, exact)
    return {"ops": ops_by_kind, "bytes": bytes_by_kind,
            "ops_total": sum(ops_by_kind.values()),
            "bytes_total": sum(bytes_by_kind.values()),
            "bytes_exact": exact_total}


def _mesh_spans_processes(mesh) -> bool:
    import jax

    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def run_data_parallel(core, program, scope: Scope, feed: Dict,
                      fetch_list: Sequence, loss_name=None, places=None,
                      build_strategy=None, return_numpy=True,
                      mesh=None, axis_name="dp"):
    """Mesh execution of a (transpiled) Program — data parallelism by
    default, and the hybrid axes when the program carries shard metadata
    from the fleet transpiler passes (_var_shard_specs / _feed_shard_specs
    / _data_axes: sharded embedding over 'mp', ring attention over 'sp',
    expert parallelism over 'ep').

    Single-process: `feed` carries the FULL batch, sharded by the
    mesh. Multi-process (the mesh spans jax processes — the reference's
    NCCL2 multi-trainer mode): each process passes its OWN batch shard,
    assembled into a global array via
    jax.make_array_from_process_local_data; fetches and updated state
    are read back from the locally-addressable replica."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if mesh is None and isinstance(places, Mesh):
        mesh = places  # CompiledProgram.with_data_parallel(places=mesh)
        places = None
    mesh = mesh or default_mesh(len(places) if places else None, axis_name)
    nranks = int(np.prod(list(mesh.shape.values())))
    multiproc = _mesh_spans_processes(mesh)

    # hybrid-parallel metadata recorded by the transpiler passes
    shard_specs = dict(getattr(program, "_var_shard_specs", None) or {})
    feed_specs = dict(getattr(program, "_feed_shard_specs", None) or {})
    mesh_axes = set(mesh.axis_names)
    data_axes = tuple(a for a in (getattr(program, "_data_axes", None)
                                  or (axis_name,)) if a in mesh_axes)
    # a pure model-parallel mesh (every mesh axis is a shard axis, no dp
    # member) legitimately has NO data axis: the full batch is
    # replicated, grads need no allreduce. Promoting a model axis to a
    # data axis here would shard the feeds and skip the wrong allreduces
    # — silently wrong gradients.
    shard_axes_used = {a for spec in shard_specs.values()
                       for a in spec if a}
    if not data_axes and (mesh.axis_names[0] not in shard_axes_used):
        data_axes = (mesh.axis_names[0],)
    for n, spec in list(shard_specs.items()) + list(feed_specs.items()):
        for a in spec:
            if a is not None and a not in mesh_axes:
                raise ValueError(
                    "var %r sharded over axis %r absent from mesh axes %s"
                    % (n, a, sorted(mesh_axes)))
    if multiproc and (shard_specs or feed_specs):
        raise NotImplementedError(
            "hybrid shard specs over a multi-process mesh")
    data_nranks = int(np.prod([mesh.shape[a] for a in data_axes]))

    sync_bn = bool(build_strategy is not None and getattr(
        build_strategy, "sync_batch_norm", False))
    if build_strategy is not None and hasattr(build_strategy,
                                              "_warn_inert"):
        build_strategy._warn_inert()
    # GradientScaleStrategy: One and Customized both mean the USER owns
    # the loss-grad scale (One = already averaged, Customized = their
    # own scale op) — only the default CoeffNumDevice applies 1/n
    # (build_strategy.h)
    scale_loss = (build_strategy is None or getattr(
        build_strategy, "gradient_scale_strategy", 0) == 0)
    # collective rewrite (insert_allreduce_ops is itself idempotent
    # per program — fleet may have transpiled already). Loss/grad
    # scaling is over the DATA axes only: model-parallel axes see the
    # same batch and their sharded grads are already complete.
    if nranks > 1 and getattr(program, "_fused_optimizer_groups", 0):
        # the single-chip fused op is invisible to insert_allreduce_ops
        # (its grads would dodge the reduction — silently divergent
        # replicas); the mesh-side equivalent of this fusion is the
        # cross-replica sharded update (PADDLE_TPU_SHARDED_UPDATE)
        raise ValueError(
            "program was rewritten by the single-chip fused-optimizer "
            "pass; unset PADDLE_TPU_FUSED_OPTIMIZER before running it "
            "on a multi-replica mesh (use PADDLE_TPU_SHARDED_UPDATE "
            "there instead)")
    if nranks > 1:
        skip_axes = getattr(program, "_allreduce_skip_grads", None) or {}
        insert_allreduce_ops(
            program, data_nranks, scale_loss=scale_loss,
            skip_grads={g for g, axes in skip_axes.items()
                        if set(axes) & set(data_axes)})
        from .transpiler import mark_sync_batch_norm

        mark_sync_batch_norm(program, sync_bn)
        # fast collective path (bucketed / quantized allreduce, sharded
        # weight update) — rewrites per-grad collectives in place; may
        # add flat optimizer-state vars sharded over the data axis, so
        # the shard-spec snapshot is refreshed below
        from .collectives import maybe_rewrite_collectives

        maybe_rewrite_collectives(program, scope, data_nranks, data_axes,
                                  build_strategy=build_strategy,
                                  multiproc=multiproc)
        shard_specs = dict(getattr(program, "_var_shard_specs", None)
                           or {})

    if not data_axes:
        ring_val = None  # collectives become identity (nranks_data = 1)
        default_feed_spec = ()  # feeds replicated across the model mesh
    else:
        ring_val = data_axes if len(data_axes) > 1 else data_axes[0]
        default_feed_spec = (data_axes[0],)

    fetch_names = tuple(f if isinstance(f, str) else f.name
                        for f in fetch_list)
    feed_vals = {}
    for name, value in (feed or {}).items():
        arr = value.array if isinstance(value, LoDTensor) else value
        if multiproc:
            # local shard -> global array over the dp axis (straight
            # from host memory: no intermediate device put)
            if getattr(arr, "is_fully_addressable", True):
                arr = jax.make_array_from_process_local_data(
                    NamedSharding(mesh, P(axis_name)), np.asarray(arr))
        else:
            arr = jnp.asarray(np.asarray(arr)) \
                if not isinstance(value, LoDTensor) else arr
        feed_vals[name] = arr
    feed_names = tuple(sorted(feed_vals))

    read_first, written, persist_written = _analyze(program)
    state = {}
    repl = NamedSharding(mesh, P()) if multiproc else None
    for n in sorted(read_first - set(feed_names)):
        var = scope.find_var(n)
        if var is None or not var.is_initialized():
            raise RuntimeError("var %r must be fed or initialized" % n)
        arr = var.raw().array
        if multiproc and getattr(arr, "is_fully_addressable", True):
            # host value / local array -> replicated global array (an
            # already-global array from the previous step passes through)
            arr = jax.make_array_from_process_local_data(
                repl, np.asarray(arr))
        state[n] = arr
    state_names = tuple(sorted(state))
    block = program.global_block()
    out_state_names = tuple(sorted(set(state_names) | persist_written))

    from .. import observability as _obs

    key = (_program_version(program), feed_names, fetch_names, state_names,
           out_state_names, _mesh_key(mesh), data_axes, sync_bn,
           tuple(sorted((k, v) for k, v in shard_specs.items())),
           tuple(sorted((k, v) for k, v in feed_specs.items())))
    hit = _dp_cache.get(key)
    if hit is None:
        # first run of this (program, mesh) pairing: statically verify
        # the rewritten IR and its collective schedule BEFORE paying
        # the compile — a malformed rewrite or a rank-divergent
        # schedule fails here with the op named, not as a hang inside
        # shard_map. Default off (PADDLE_TPU_VERIFY_IR); cache hits
        # never reach this branch, so steady-state cost is zero.
        from ..analysis import maybe_verify_program

        maybe_verify_program(program, where="parallel.engine",
                             fetch_names=fetch_names, nranks=nranks,
                             scope=scope)
        _obs.inc("parallel.compiles")
        coll_est = _estimate_collective_bytes(program, state)
        def shard_step(state_d, feeds_d, seed):
            with ring_axis_guard({0: ring_val, -1: ring_val}), \
                    mesh_axes_guard(mesh_axes):
                env = dict(state_d)
                env.update(feeds_d)
                _trace_block(block, env, seed)
                fetches = [
                    jax.lax.all_gather(env[n], data_axes) if data_axes
                    else env[n]
                    for n in fetch_names
                ]
                new_state = {n: env[n] for n in out_state_names if n in env}
                return fetches, new_state

        mapped = _shard_map(
            shard_step, mesh,
            in_specs=({n: P(*shard_specs.get(n, ()))
                       for n in state_names},
                      {n: P(*feed_specs.get(n, default_feed_spec))
                       for n in feed_names}, P()),
            out_specs=([P() for _ in fetch_names],
                       {n: P(*shard_specs.get(n, ()))
                        for n in out_state_names}),
        )
        fn = jax.jit(mapped, donate_argnums=(0,))
        hit = (fn, coll_est)
        _dp_cache[key] = hit
    fn, coll_est = hit

    import time as _time

    from ..observability import distributed as _dtrace

    global _sync_round
    round_no = _sync_round
    _sync_round += 1
    t_step = _time.perf_counter() if _obs.enabled() else None
    # the step span joins the job trace (launcher-minted
    # PADDLE_TPU_TRACE_ID) under a round id every rank derives
    # identically — a dp sync round is ONE cross-process timeline, the
    # same propagation contract ps_rpc and serving already keep
    with _obs.tracing.span("parallel/step", cat="step", ranks=nranks,
                           round=round_no,
                           **_dtrace.fleet_round_args(round_no)):
        fetches, new_state = fn(
            state, feed_vals,
            jnp.uint32(core.rng.next_seed(0) ^
                       ((core.rng.step * 2654435761) & 0xFFFFFFFF)))
    core.rng.advance()
    if t_step is not None:
        _obs.inc("parallel.steps")
        _obs.observe("parallel.step_ms",
                     (_time.perf_counter() - t_step) * 1e3)
        _obs.inc("parallel.collective_ops", coll_est["ops_total"])
        _obs.inc("parallel.collective_bytes", coll_est["bytes_total"])
        for k, n in coll_est["ops"].items():
            _obs.inc("parallel.collective_ops", n, kind=k)
        for k, b in coll_est["bytes"].items():
            _obs.inc("parallel.collective_bytes", b, kind=k)
        saved = coll_est["bytes_exact"] - coll_est["bytes_total"]
        if saved > 0:
            _obs.inc("parallel.collective_bytes_saved", saved)

    def _local(v):
        """A locally-readable copy of a (replicated) result: under a
        multi-process mesh the global Array is not fully addressable,
        so read this process's replica shard."""
        if multiproc and hasattr(v, "addressable_shards"):
            return v.addressable_shards[0].data
        return v

    for n, v in new_state.items():
        # keep the global (replicated) array in scope: the next step
        # feeds it straight back without a host round-trip
        scope.var(n).get_tensor()._array = v
    # sampled in-production capture (PADDLE_TPU_SAMPLE_EVERY): every
    # Nth mesh step re-profiles the live (program, scope, feed) into a
    # rolling report for the steering daemon — default off, one branch.
    # AFTER the scope writeback: the step donated the previous state
    # buffers, so the profiler must read the freshly-stored arrays.
    from ..observability import capture as _capture

    _capture.maybe_sample_step("parallel", program, scope, feed,
                               mesh=mesh, axis_name=axis_name)
    results = []
    for name, v in zip(fetch_names, fetches):
        results.append(np.asarray(_local(v)) if return_numpy
                       else _local(v))
    return results
