"""Training THROUGH While bodies (reference while_grad,
controlflow/while_op.cc WhileGradOp) — the round-3 gap where grads
silently did not flow into params used inside a loop.

The grad sub-block is generated from the body by the shared backward
engine; while_grad replays each saved trip in reverse from its pre-trip
snapshot (remat), threads carry grads, and accumulates param grads.
Oracle: the same computation unrolled statically."""
import numpy as np
import pytest

import paddle_tpu as fluid

T, B, D = 4, 3, 5


def _build_while_rnn(carry_stop_gradient=False):
    """h_{t+1} = tanh(h_t @ W + x); loss = mean(h_T)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[B, D], dtype="float32")
        w = fluid.layers.create_parameter([D, D], "float32", name="w_rnn")
        h = fluid.layers.fill_constant([B, D], "float32", 0.0)
        if not carry_stop_gradient:
            h.stop_gradient = False
        i = fluid.layers.fill_constant([1], "int64", 0)
        n = fluid.layers.fill_constant([1], "int64", T)
        cond = fluid.layers.less_than(i, n)
        wh = fluid.layers.While(cond)
        with wh.block():
            nh = fluid.layers.tanh(
                fluid.layers.elementwise_add(
                    fluid.layers.matmul(h, w), x))
            fluid.layers.assign(nh, h)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(i, n, cond=cond)
        loss = fluid.layers.reduce_mean(h)
        fluid.optimizer.SGDOptimizer(0.5).minimize(loss)
    return main, startup, loss


def _build_unrolled():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[B, D], dtype="float32")
        w = fluid.layers.create_parameter([D, D], "float32", name="w_ur")
        h = fluid.layers.fill_constant([B, D], "float32", 0.0)
        h.stop_gradient = False
        for _ in range(T):
            h = fluid.layers.tanh(
                fluid.layers.elementwise_add(
                    fluid.layers.matmul(h, w), x))
        loss = fluid.layers.reduce_mean(h)
        fluid.optimizer.SGDOptimizer(0.5).minimize(loss)
    return main, startup, loss


@pytest.mark.parametrize("carry_stop_gradient", [False, True])
def test_while_training_matches_unrolled(carry_stop_gradient):
    """Both carry flavors must match: stop_gradient=True is
    fill_constant's DEFAULT (the natural user code) — the carry grad
    must still thread through trips internally even when the user never
    asked for d(loss)/d(h0)."""
    rng = np.random.RandomState(0)
    xv = rng.randn(B, D).astype("float32")
    w0 = (rng.randn(D, D) * 0.4).astype("float32")

    import jax.numpy as jnp

    def build_while():
        return _build_while_rnn(carry_stop_gradient)

    results = {}
    for name, build, wname in (("while", build_while, "w_rnn"),
                               ("unrolled", _build_unrolled, "w_ur")):
        main, startup, loss = build()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            scope.var(wname).get_tensor()._array = jnp.asarray(w0)
            losses = []
            for _ in range(3):
                (l,) = exe.run(main, feed={"x": xv}, fetch_list=[loss])
                losses.append(float(np.ravel(l)[0]))
            w_fin = np.asarray(scope.find_var(wname).raw().array)
        results[name] = (losses, w_fin)

    l_w, w_w = results["while"]
    l_u, w_u = results["unrolled"]
    # the while program must actually TRAIN (the round-3 silent bug:
    # identical losses step after step because w never updated)
    assert abs(l_w[1] - l_w[0]) > 1e-6, l_w
    np.testing.assert_allclose(l_w, l_u, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w_w, w_u, rtol=1e-4, atol=1e-6)


def test_while_grad_zero_trip():
    """A loop whose condition is false from the start: carries pass
    grads through unchanged; the program still trains the ops outside
    the loop."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[B, D], dtype="float32")
        w = fluid.layers.create_parameter([D, D], "float32", name="w_z")
        h = fluid.layers.matmul(x, w)
        i = fluid.layers.fill_constant([1], "int64", 5)
        n = fluid.layers.fill_constant([1], "int64", 3)
        cond = fluid.layers.less_than(i, n)  # False immediately
        wh = fluid.layers.While(cond)
        with wh.block():
            nh = fluid.layers.scale(h, scale=2.0)
            fluid.layers.assign(nh, h)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(i, n, cond=cond)
        loss = fluid.layers.reduce_mean(h)
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    rng = np.random.RandomState(1)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        w0 = np.asarray(scope.find_var("w_z").raw().array).copy()
        (l0,) = exe.run(main, feed={"x": rng.randn(B, D).astype(
            "float32")}, fetch_list=[loss])
        w1 = np.asarray(scope.find_var("w_z").raw().array)
    assert np.isfinite(float(np.ravel(l0)[0]))
    assert np.abs(w1 - w0).max() > 1e-8  # grads flowed around the loop


def test_dynamic_rnn_trains_numeric_grad():
    """Training THROUGH DynamicRNN (while + rank-table arrays): the
    analytic weight gradient matches finite differences through the
    full LoD pipeline, and SGD steps actually change the loss —
    closing the round-3 'forward-only DynamicRNN' gap."""
    from paddle_tpu.core.tensor import LoDTensor

    D_in, H = 3, 4
    lengths = [3, 1, 2]
    rng = np.random.RandomState(11)
    total = sum(lengths)
    x_np = rng.randn(total, D_in).astype("float32")
    x_t = LoDTensor(x_np)
    offs = [0]
    for ln in lengths:
        offs.append(offs[-1] + ln)
    x_t.set_lod([offs])

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="seq", shape=[-1, D_in],
                           dtype="float32", lod_level=1)
            drnn = fluid.layers.DynamicRNN()
            with drnn.block():
                word = drnn.step_input(x)
                prev = drnn.memory(shape=[H], value=0.0)
                hidden = fluid.layers.fc(
                    [word, prev], size=H, act="tanh",
                    param_attr=[fluid.ParamAttr(name="gwx"),
                                fluid.ParamAttr(name="gwh")],
                    bias_attr=fluid.ParamAttr(name="gb"))
                drnn.update_memory(prev, hidden)
                drnn.output(hidden)
            out = drnn()
            loss = fluid.layers.reduce_mean(
                fluid.layers.sequence_pool(out, pool_type="SUM"))
            fluid.optimizer.SGDOptimizer(0.0).minimize(loss)  # lr 0:
            # params frozen so repeated runs measure the same point
        return main, startup, loss

    main, startup, loss = build()
    scope = fluid.Scope()
    import jax.numpy as jnp

    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)

        def run_loss():
            (l,) = exe.run(main, feed={"seq": x_t}, fetch_list=[loss])
            return float(np.ravel(l)[0])

        run_loss()
        g_wx = np.asarray(scope.find_var("gwx@GRAD").raw().array)
        wx = np.asarray(scope.find_var("gwx").raw().array).copy()
        # finite differences on three elements of W_x
        eps = 1e-3
        for idx in [(0, 0), (1, 2), (2, 3)]:
            for sgn, store in ((+1, "p"), (-1, "m")):
                w2 = wx.copy()
                w2[idx] += sgn * eps
                scope.var("gwx").get_tensor()._array = jnp.asarray(w2)
                if sgn > 0:
                    lp = run_loss()
                else:
                    lm = run_loss()
            scope.var("gwx").get_tensor()._array = jnp.asarray(wx)
            num = (lp - lm) / (2 * eps)
            np.testing.assert_allclose(g_wx[idx], num, rtol=5e-3,
                                       atol=1e-4)

    # and with a real lr, the loss moves
    main2, startup2, loss2 = build()
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup2)
        # swap lr var to 0.5 (created by the optimizer as a constant)
        for name in main2.global_block().vars:
            if "learning_rate" in name:
                scope2.var(name).get_tensor()._array = jnp.asarray(
                    np.asarray([0.5], "float32"))
        losses = []
        for _ in range(4):
            (l,) = exe2.run(main2, feed={"seq": x_t},
                            fetch_list=[loss2])
            losses.append(float(np.ravel(l)[0]))
    assert all(np.isfinite(losses)), losses
    assert abs(losses[-1] - losses[0]) > 1e-6, losses


def test_dynamic_rnn_input_grad_stable_across_runs():
    """Array-valued input grads must be RECOMPUTED per run, not
    accumulated into a stale grad array from the previous exe.run."""
    from paddle_tpu.core.tensor import LoDTensor

    D_in, H = 3, 4
    rng = np.random.RandomState(13)
    x_np = rng.randn(4, D_in).astype("float32")
    x_t = LoDTensor(x_np)
    x_t.set_lod([[0, 2, 4]])

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="seq", shape=[-1, D_in], dtype="float32",
                       lod_level=1)
        x.stop_gradient = False
        drnn = fluid.layers.DynamicRNN()
        with drnn.block():
            word = drnn.step_input(x)
            prev = drnn.memory(shape=[H], value=0.0)
            hidden = fluid.layers.fc(
                [word, prev], size=H, act="tanh",
                param_attr=[fluid.ParamAttr(name="swx"),
                            fluid.ParamAttr(name="swh")],
                bias_attr=False)
            drnn.update_memory(prev, hidden)
            drnn.output(hidden)
        out = drnn()
        loss = fluid.layers.reduce_mean(
            fluid.layers.sequence_pool(out, pool_type="SUM"))
        fluid.optimizer.SGDOptimizer(0.0).minimize(loss)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        sums = []
        for _ in range(3):
            exe.run(main, feed={"seq": x_t}, fetch_list=[loss])
            g = np.asarray(scope.find_var("seq@GRAD").raw().array)
            sums.append(float(np.abs(g).sum()))
    # identical every run (lr=0 keeps the function fixed)
    np.testing.assert_allclose(sums[1], sums[0], rtol=1e-6)
    np.testing.assert_allclose(sums[2], sums[0], rtol=1e-6)
