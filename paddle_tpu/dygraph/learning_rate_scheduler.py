"""Dygraph LR schedulers.

Parity: /root/reference/python/paddle/fluid/dygraph/learning_rate_scheduler.py.
Each is a callable returning the current lr (float); `step()` advances.
"""
from __future__ import annotations

import math

__all__ = ["LearningRateDecay", "PiecewiseDecay", "NaturalExpDecay",
           "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
           "CosineDecay", "NoamDecay"]


class LearningRateDecay:
    def __init__(self, begin=0, step=1, dtype="float32"):
        self.step_num = begin
        self.step_size = step

    def __call__(self):
        lr = self.step_impl()
        self.step_num += self.step_size
        return lr

    def current(self):
        return self.step_impl()

    def step_impl(self):
        raise NotImplementedError


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin=0, step=1):
        super().__init__(begin, step)
        self.boundaries = boundaries
        self.values = values

    def step_impl(self):
        for i, b in enumerate(self.boundaries):
            if self.step_num < b:
                return self.values[i]
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr, self.decay_steps = learning_rate, decay_steps
        self.decay_rate, self.staircase = decay_rate, staircase

    def step_impl(self):
        d = self.step_num / self.decay_steps
        if self.staircase:
            d = math.floor(d)
        return self.lr * math.exp(-self.decay_rate * d)


class ExponentialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr, self.decay_steps = learning_rate, decay_steps
        self.decay_rate, self.staircase = decay_rate, staircase

    def step_impl(self):
        d = self.step_num / self.decay_steps
        if self.staircase:
            d = math.floor(d)
        return self.lr * (self.decay_rate ** d)


class InverseTimeDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr, self.decay_steps = learning_rate, decay_steps
        self.decay_rate, self.staircase = decay_rate, staircase

    def step_impl(self):
        d = self.step_num / self.decay_steps
        if self.staircase:
            d = math.floor(d)
        return self.lr / (1 + self.decay_rate * d)


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=0.0001,
                 power=1.0, cycle=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr, self.decay_steps = learning_rate, decay_steps
        self.end_lr, self.power, self.cycle = end_learning_rate, power, cycle

    def step_impl(self):
        step = self.step_num
        decay_steps = self.decay_steps
        if self.cycle:
            div = math.ceil(step / decay_steps) if step > 0 else 1
            decay_steps = decay_steps * div
        else:
            step = min(step, decay_steps)
        return (self.lr - self.end_lr) * \
            (1 - step / decay_steps) ** self.power + self.end_lr


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1):
        super().__init__(begin, step)
        self.lr = learning_rate
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def step_impl(self):
        cur_epoch = math.floor(self.step_num / self.step_each_epoch)
        return self.lr * 0.5 * (math.cos(cur_epoch * math.pi / self.epochs) + 1)


class NoamDecay(LearningRateDecay):
    def __init__(self, d_model, warmup_steps, begin=1, step=1,
                 learning_rate=1.0):
        super().__init__(begin, step)
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        self.base_lr = learning_rate

    def step_impl(self):
        step = max(self.step_num, 1)
        a = step ** -0.5
        b = self.warmup_steps ** -1.5 * step
        return self.base_lr * (self.d_model ** -0.5) * min(a, b)
