"""Placement synthesis (ISSUE 15): search the parallelism-plan space
instead of hoping the operator picked well.

Three pieces over two existing substrates — the measured profile
reports (PR 7/10) and the machine-checkable plan safety net (PR 12):

- :mod:`.cost_model` — per-collective ``a + b*bytes`` terms fitted to
  a saved step-profile report (hand-estimate fallback), every score
  tagged ``fitted`` vs ``analytic``;
- :mod:`.search` — a beam over dp/mp/pp/sp/ep factorizations,
  sharded-update, bucket layouts, reduction-strategy spellings,
  per-bucket quantization (+ EQuARX error feedback) and async
  start/await scheduling, where EVERY candidate is rewritten
  symbolically and gated through ``verify_program`` +
  ``check_cross_rank`` before it could ever be traced;
- :mod:`.plan` — the winning configuration as a self-contained JSON
  artifact the engine loads via ``PADDLE_TPU_PLACEMENT_PLAN`` (the
  ``PADDLE_TPU_BUCKET_PROFILE`` pattern), emitted per model by
  ``tools/placement_search.py``.
"""
from __future__ import annotations

from .cost_model import (CostModel, analytic_cost_model,  # noqa: F401
                         fit_cost_model)
from .plan import (PLAN_ENV, PlacementPlan, active_plan,  # noqa: F401
                   load_plan, save_plan)
from .search import (Candidate, enumerate_meshes,  # noqa: F401
                     model_capabilities, search_placement)

__all__ = [
    "CostModel", "fit_cost_model", "analytic_cost_model",
    "PlacementPlan", "load_plan", "save_plan", "active_plan",
    "PLAN_ENV", "Candidate", "enumerate_meshes", "model_capabilities",
    "search_placement",
]
