"""Oxford-102 flowers reader creators (reference
python/paddle/dataset/flowers.py).

Sample contract: (image float32[3*H*W] CHW normalized to [0,1] after
simple_transform, label int 0..101). Synthetic fallback: class-tinted
noise images, deterministic.
"""
from __future__ import annotations

import os

import numpy as np

from .common import DATA_HOME
from .image import simple_transform

__all__ = ["train", "test", "valid"]

_CLASSES = 102


def _data_dir():
    return os.path.join(DATA_HOME, "flowers")


def _synthetic_reader(n, seed, mapper=None):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, _CLASSES))
            img = (rng.rand(64, 64, 3) * 60).astype("uint8")
            img[:, :, label % 3] += np.uint8(120 + (label % 17) * 4)
            sample = simple_transform(img, 32, 32, is_train=False)
            yield sample, label

    return reader


def _file_reader(list_name, mapper):
    import tarfile

    import scipy.io  # noqa: F401  (labels are a .mat in the real set)

    raise NotImplementedError(
        "real flowers archives present but the offline parser only "
        "supports the synthetic path in this build; remove %s to use "
        "synthetic data" % _data_dir())


def train(mapper=None, buffered_size=1024, use_xmap=False, cycle=False):
    from .common import cycled

    if os.path.exists(os.path.join(_data_dir(), "102flowers.tgz")):
        r = _file_reader("trnid", mapper)
    else:
        r = _synthetic_reader(2048, seed=50)
    return cycled(r) if cycle else r


def test(mapper=None, buffered_size=1024, use_xmap=False, cycle=False):
    from .common import cycled

    if os.path.exists(os.path.join(_data_dir(), "102flowers.tgz")):
        r = _file_reader("tstid", mapper)
    else:
        r = _synthetic_reader(256, seed=51)
    return cycled(r) if cycle else r


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    if os.path.exists(os.path.join(_data_dir(), "102flowers.tgz")):
        return _file_reader("valid", mapper)
    return _synthetic_reader(256, seed=52)
