"""Static-graph autodiff: append_backward / gradients.

Behavioral parity with /root/reference/python/paddle/fluid/backward.py
(:1145 append_backward, :366 _addup_repetitive_outputs_, :448
_remove_no_grad_branch_): walks the block in reverse, appends
``<type>_grad`` ops, inserts ``sum`` ops where a forward var fans out to
several consumers, and respects stop_gradient / no_grad_set.

The grad ops themselves are the auto-VJP ops from the registry (or
hand-registered customs), so unlike the reference there is no per-op C++
GradOpMaker protocol to mirror — the maker here only decides *wiring*
(which slots are bound), and shapes are copied from the forward vars
instead of re-inferred.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

from . import framework
from .core.registry import GRAD_SUFFIX, OpInfoMap, ensure_grad_op
from .utils import unique_name


def _op_io(block, op):
    """Effective (inputs, outputs) of an op for dataflow analysis. A
    `while` op declares no tensors itself — its body reads/writes
    parent vars by name (while_op.cc semantics), so its effective IO is
    the sub-block's external read/write sets restricted to
    parent-visible vars."""
    if op.type == "while" and op.attrs.get("sub_block") is not None:
        from .core.compiler_engine import _block_rw

        written, read_first = _block_rw(op.attrs["sub_block"])
        ins = [n for n in read_first
               if block._find_var_recursive(n) is not None]
        outs = [n for n in written
                if block._find_var_recursive(n) is not None]
        return (list(op.input_arg_names) + ins,
                list(op.output_arg_names) + outs)
    return list(op.input_arg_names), list(op.output_arg_names)


def _find_op_path(block, loss_name: str, req: Set[str]) -> List[int]:
    """Indices of ops that both (a) depend on a grad-requiring var and
    (b) contribute to the loss."""
    # forward reachability of req
    contributes: Set[str] = set(req)
    fwd_ops: Set[int] = set()
    for i, op in enumerate(block.ops):
        ins, outs = _op_io(block, op)
        if any(n in contributes for n in ins):
            fwd_ops.add(i)
            contributes.update(outs)
    # backward reachability from loss
    needed: Set[str] = {loss_name}
    path: List[int] = []
    for i in reversed(range(len(block.ops))):
        op = block.ops[i]
        ins, outs = _op_io(block, op)
        if i in fwd_ops and any(n in needed for n in outs):
            path.append(i)
            needed.update(ins)
    return list(reversed(path))


def _requires_grad_set(block, parameter_list=None, no_grad_set=None) -> Set[str]:
    no_grad = set(no_grad_set or ())
    req: Set[str] = set()
    if parameter_list is not None:
        for p in parameter_list:
            name = p if isinstance(p, str) else p.name
            if name not in no_grad:
                req.add(name)
    else:
        for p in block.program.all_parameters():
            if getattr(p, "trainable", True) and not p.stop_gradient \
                    and p.name not in no_grad:
                req.add(p.name)
    # any non-stop-gradient var is a valid diff leaf too (matches
    # reference: stop_gradient=False inputs get gradients)
    for v in block.vars.values():
        if not v.stop_gradient and v.name not in no_grad:
            req.add(v.name)
    return req


def _ensure_grad_var(block, fwd_name: str, grad_name: str):
    fwd = block._find_var_recursive(fwd_name)
    if block.has_var_local(grad_name):
        return block.vars[grad_name]
    v = block.create_var(
        name=grad_name,
        shape=fwd.shape if fwd is not None else None,
        dtype=fwd.dtype if fwd is not None else "float32",
        persistable=False,
        # grad vars are differentiable quantities: a later
        # append_backward over this program (gradient penalty /
        # grad-of-grad) must be able to flow gradients through them —
        # stop_gradient=True here would put every @GRAD var in that
        # pass's no_grad set and silently sever the double-grad path
        stop_gradient=False,
    )
    return v


def append_backward(
    loss,
    parameter_list=None,
    no_grad_set=None,
    callbacks=None,
    checkpoints=None,
):
    """Append grad ops computing d(loss)/d(var); returns
    [(param, param_grad_var)] like the reference (backward.py:1145)."""
    block = loss.block
    program = block.program
    program._appending_grad_times += 1
    # pass-aware grad naming (reference backward.py _rename_grad_): a
    # second pass over a program already holding grad vars must not
    # clobber the first pass's canonical @GRAD names — its canonicals
    # get an @<pass> suffix when the base name predates this pass
    prev = _PASS_STATE.copy()
    _PASS_STATE["times"] = program._appending_grad_times
    _PASS_STATE["preexisting"] = frozenset(
        n for b in program.blocks for n in b.vars)
    try:
        with program._backward_role_guard():
            return _append_backward_impl(loss, block, program,
                                         parameter_list, no_grad_set,
                                         checkpoints)
    finally:
        _PASS_STATE.clear()
        _PASS_STATE.update(prev)


_PASS_STATE: Dict = {}


def grad_name_for(n: str) -> str:
    """Canonical grad-var name for ``n`` in the CURRENT backward pass:
    the plain ``n@GRAD`` unless an earlier pass already owns it."""
    base = framework.grad_var_name(n)
    if _PASS_STATE.get("times", 1) > 1 \
            and base in _PASS_STATE.get("preexisting", ()):
        return "%s@%d" % (base, _PASS_STATE["times"])
    return base


def _emit_recompute_ops(block, path, checkpoints) -> Dict[str, str]:
    """Append renamed copies of the forward path ops (checkpoint vars and
    externally-produced vars are read as-is). Returns the old->new name
    map the grad binding uses for forward-value references."""
    keep = {c.name if hasattr(c, "name") else str(c) for c in checkpoints}
    rename: Dict[str, str] = {}
    for idx in path:
        op = block.ops[idx]
        outs_to_rename = [n for n in op.output_arg_names
                          if n and n not in keep]
        if not outs_to_rename:
            continue  # only checkpoint outputs: stored, not recomputed
        new_inputs = {slot: [rename.get(n, n) for n in names]
                      for slot, names in op.inputs.items()}
        new_outputs = {}
        for slot, names in op.outputs.items():
            outs = []
            for n in names:
                if not n:
                    outs.append(n)
                    continue
                # NEVER rebind the original name: checkpoint values are
                # stored (reads go to the original), and persistable
                # outputs (BN running stats) must not update twice.
                nn = n + "@RECOMPUTE"
                if nn not in block.vars:
                    v = block._find_var_recursive(n)
                    nv = block.create_var(
                        name=nn,
                        shape=None if v is None else v.shape,
                        dtype="float32" if v is None else v.dtype)
                    nv.stop_gradient = True
                if n not in keep:
                    rename[n] = nn
                outs.append(nn)
            new_outputs[slot] = outs
        attrs = dict(op.attrs)
        attrs.setdefault("_fwd_op_id", op._id or 0)
        block.append_op(op.type, inputs=new_inputs, outputs=new_outputs,
                        attrs=attrs, infer_shape=False)
    return rename


def _append_backward_impl(loss, block, program, parameter_list=None,
                          no_grad_set=None, checkpoints=None):

    no_grad = set()
    for b in program.blocks:
        for v in b.vars.values():
            if v.stop_gradient:
                no_grad.add(v.name)
    user_no_grad = {n if isinstance(n, str) else n.name
                    for n in (no_grad_set or ())}
    no_grad |= user_no_grad

    # a float var REWRITTEN by a while body is no longer the
    # stop-gradient constant its initializer produced (fill_constant
    # marks outputs stop_gradient=True by default — the natural init for
    # a loop carry): severing it here would cut the grad chain through
    # the loop entirely. An EXPLICIT user no_grad_set entry still wins.
    for op in block.ops:
        sub = op.attrs.get("sub_block") if op.type == "while" else None
        if sub is None:
            continue
        from .core.compiler_engine import _block_rw

        written, _ = _block_rw(sub)
        for n in written:
            v = block._find_var_recursive(n)
            if v is not None and _is_float_var(v) \
                    and n not in user_no_grad:
                no_grad.discard(n)

    req = _requires_grad_set(block, parameter_list, no_grad)
    # propagate requires-grad forward through the op list
    diffable: Set[str] = set(req)
    for op in block.ops:
        if op.type == "while":
            ins, outs = _op_io(block, op)
            if any(n in diffable for n in ins):
                for n in outs:
                    if n not in no_grad:
                        diffable.add(n)
            continue
        info = _op_info(op.type)
        if info is None or info.grad is None and not _has_grad_op(op.type):
            continue
        if any(n in diffable for n in op.input_arg_names):
            for n in op.output_arg_names:
                if n not in no_grad:
                    diffable.add(n)

    path = _find_op_path(block, loss.name, req)

    # Recompute (reference backward.py:623
    # _append_backward_ops_with_checkpoints_): re-emit the forward ops of
    # each inter-checkpoint segment at the start of the backward region
    # with renamed outputs; grad ops then read the RECOMPUTED values, so
    # the original intermediates have no backward consumers and die
    # early. RNG ops re-emit with the original op's seed stream so
    # dropout masks match. (Under whole-program compilation XLA may CSE
    # a re-emitted op back onto its original when that is cheaper —
    # memory behavior is then the compiler's call, never worse.)
    recompute_rename: Dict[str, str] = {}
    if checkpoints:
        recompute_rename = _emit_recompute_ops(block, path, checkpoints)

    # Seed d(loss)/d(loss) = 1
    loss_grad_name = grad_name_for(loss.name)
    _ensure_grad_var(block, loss.name, loss_grad_name)
    block.append_op(
        "fill_constant",
        inputs={},
        outputs={"Out": loss_grad_name},
        attrs={
            "shape": list(loss.shape or ()),
            "value": 1.0,
            "dtype": _dtype_enum(loss.dtype),
            "force_cpu": False,
        },
        infer_shape=False,
    )

    # pending grads per forward var (producers merge on arrival)
    pending: Dict[str, List[str]] = {loss.name: [loss_grad_name]}
    grad_to_var: Dict[str, str] = {loss_grad_name: loss.name}
    finalize = make_finalize(block, pending)

    _emit_grad_ops(block, [block.ops[i] for i in path], pending,
                   finalize, diffable, no_grad, recompute_rename,
                   grad_to_var)

    # finalize leaves (parameters & data): merge their partial grads
    params_and_grads = []
    target_params = (
        [p if isinstance(p, framework.Variable) else block.var(p)
         for p in parameter_list]
        if parameter_list is not None
        else block.program.all_parameters()
    )
    for p in target_params:
        g = finalize(p.name)
        if g is None:
            continue
        params_and_grads.append((p, block.var(g)))
    return params_and_grads


def make_finalize(block, pending: Dict[str, List[str]],
                  clear_on_merge: bool = False):
    """Finalize closure: merge a var's pending partial grads into its
    canonical @GRAD name (sum op emitted into ``block``).
    ``clear_on_merge`` empties the pending list after the merge — used
    inside while-grad sub-blocks, where the same NAME is both the loop
    carry's incoming grad (consumed by the write op's grad) and later
    the pre-value's partials; without clearing, the consumed canonical
    would be double-counted at the end-of-block merge."""

    def finalize(var_name: str) -> Optional[str]:
        glist = pending.get(var_name)
        if not glist:
            return None
        canonical = grad_name_for(var_name)
        if len(glist) == 1 and glist[0] == canonical:
            if clear_on_merge:
                pending[var_name] = []
            return canonical
        _ensure_grad_var(block, var_name, canonical)
        block.append_op(
            "sum",
            inputs={"X": list(glist)},
            outputs={"Out": canonical},
            infer_shape=False,
        )
        pending[var_name] = [] if clear_on_merge else [canonical]
        return canonical

    return finalize


def _emit_grad_ops(block, fwd_ops, pending, finalize, diffable, no_grad,
                   recompute_rename, grad_to_var):
    """Reverse-walk ``fwd_ops`` appending grad ops into ``block`` — the
    shared engine behind append_backward AND while-body grad blocks."""
    for op in reversed(fwd_ops):
        if op.type == "while":
            _emit_while_grad(block, op, pending, finalize, diffable,
                             no_grad, grad_to_var)
            continue
        info = _op_info(op.type)
        if info is None:
            continue
        grad_type = op.type + "_grad"
        # A callable grad maker owns its op's backward entirely (custom
        # output binding, e.g. data_norm's in-place stat rebind) — it wins
        # even when a <type>_grad op is also registered for it to emit.
        if callable(info.grad) and info.grad != "auto":
            info.grad(block, op, pending, finalize)
            continue
        if not _has_grad_op(op.type):
            # info.grad is None or "auto" with no grad op: grads don't flow
            continue
        ginfo = OpInfoMap.instance().get(grad_type)

        # which outputs have incoming grads?
        out_grads = {}
        has_grad = False
        for slot in info.outputs:
            names = op.output(slot.name)
            if not names:
                continue
            gnames = []
            for n in names:
                g = finalize(n)
                gnames.append(g if g is not None else "")
                if g is not None:
                    has_grad = True
            if any(gnames):
                out_grads[slot.name + GRAD_SUFFIX] = gnames
        if not has_grad:
            continue

        # bind inputs: forward ins + out grads. Forward VALUE references
        # go through the recompute rename (grad math reads recomputed
        # activations); grad accumulation stays on original names.
        g_inputs = {}
        for slot in info.inputs:
            names = op.input(slot.name)
            if names:
                g_inputs[slot.name] = [recompute_rename.get(n, n)
                                       for n in names]
        g_inputs.update(out_grads)
        # some custom grad ops consume forward outputs too (slot name match)
        for slot in ginfo.inputs:
            if slot.name in g_inputs or slot.name.endswith(GRAD_SUFFIX):
                continue
            if slot.name in op.outputs:
                g_inputs[slot.name] = [recompute_rename.get(n, n)
                                       for n in op.outputs[slot.name]]

        # outputs: a fresh partial-grad name per diffable input var.
        # no_grad forward slots (labels, masks) never get a grad binding —
        # the grad kernel won't write them, and binding one would leave an
        # uninitialized var feeding the downstream sum (ADVICE r1 #3).
        g_outputs = {}
        for slot in info.inputs:
            if slot.no_grad:
                continue
            names = op.input(slot.name)
            if not names:
                continue
            gnames = []
            bind = False
            for n in names:
                if n in diffable and n not in no_grad:
                    if n in pending and pending[n]:
                        gname = "%s@RENAME@%d" % (grad_name_for(n),
                                                  len(pending[n]))
                    else:
                        gname = grad_name_for(n)
                    _ensure_grad_var(block, n, gname)
                    pending.setdefault(n, []).append(gname)
                    grad_to_var[gname] = n
                    gnames.append(gname)
                    bind = True
                else:
                    gnames.append("")
            if bind:
                g_outputs[slot.name + GRAD_SUFFIX] = gnames

        if not g_outputs:
            continue

        g_attrs = dict(op.attrs)
        g_attrs["_fwd_op_id"] = op._id
        block.append_op(grad_type, g_inputs, g_outputs, g_attrs,
                       infer_shape=False)


def _is_float_var(v) -> bool:
    if v is None or v.dtype is None:
        return True  # unknown: let the runtime decide
    return str(v.dtype).startswith(("float", "bfloat"))


def _emit_while_grad(block, op, pending, finalize, diffable, no_grad,
                     grad_to_var):
    """Backward THROUGH a while loop (reference while_grad,
    controlflow/while_op.cc WhileGradOp): build a grad sub-block from
    the body's ops and append ONE while_grad host op that replays the
    body per saved step in reverse, threading carry grads and
    accumulating parameter grads.

    Supported body shape (the RNN pattern): each parent-written carry is
    written once per trip, with every body read of it happening before
    the write (reads see the previous trip's value)."""
    from .core.compiler_engine import _block_rw

    sub = op.attrs.get("sub_block")
    if sub is None:
        return
    program = block.program
    written_all, read_first = _block_rw(sub)
    parent_written = sorted(
        n for n in written_all
        if block._find_var_recursive(n) is not None)
    parent_read = sorted(
        n for n in read_first
        if block._find_var_recursive(n) is not None)
    carries = sorted(set(parent_written) & set(read_first))

    # incoming grads of the loop's outputs (the final written values)
    incoming = {}
    for w in parent_written:
        if not _is_float_var(block._find_var_recursive(w)):
            continue
        g = finalize(w)
        if g is not None:
            incoming[w] = g
            # fully consumed here: producers BEFORE the loop receive the
            # pre-loop grad from while_grad's outputs, not this one
            pending[w] = []
    if not incoming:
        return

    targets = [r for r in parent_read
               if r in diffable and r not in no_grad
               and _is_float_var(block._find_var_recursive(r))]
    # carries must be grad-THREADED through trips even when
    # stop_gradient (fill_constant's default!) excludes them from
    # user-visible grads: without a per-trip carry grad, every replayed
    # trip would be reseeded with the stale final-output gradient
    float_carries = [c for c in carries
                     if _is_float_var(block._find_var_recursive(c))]
    thread_targets = sorted(set(targets) | set(float_carries))
    if not thread_targets:
        return

    # diffable set inside the body: threaded vars + anything they reach.
    # Carries leave the no_grad set for the SUB-generation only (their
    # internal grads are loop plumbing, not user-visible outputs).
    no_grad2 = set(no_grad) - set(float_carries)
    diffable2 = set(diffable) | set(thread_targets)
    for bop in sub.ops:
        if any(n in diffable2 for n in bop.input_arg_names):
            for n in bop.output_arg_names:
                if n not in no_grad2:
                    diffable2.add(n)

    gblock = program._create_block()
    pending2: Dict[str, List[str]] = {}
    seed_names = {}
    # seed EVERY float carry, not only those with outer grads: a carry
    # without a user-visible consumer can still carry cross-trip
    # gradient between interacting carries (h1 <- f(h2) <- previous
    # trip's h1); the host zero-seeds entries with no value yet
    seeded = sorted(set(incoming) | set(float_carries))
    for w in seeded:
        gname = grad_name_for(w)
        _ensure_grad_var(gblock, w, gname)
        pending2[w] = [gname]
        seed_names[w] = gname
    finalize2 = make_finalize(gblock, pending2, clear_on_merge=True)
    from .ops.control_flow_ops import _IN_WHILE_GRAD_GEN

    _IN_WHILE_GRAD_GEN.append(True)
    try:
        _emit_grad_ops(gblock, list(sub.ops), pending2, finalize2,
                       diffable2, no_grad2, {}, {})
    finally:
        _IN_WHILE_GRAD_GEN.pop()
    inner_grads = {}
    for r in thread_targets:
        g = finalize2(r)
        if g is not None:
            inner_grads[r] = g
    program._rollback()
    if not inner_grads:
        return

    tgt_list = sorted(inner_grads)
    # user-visible outputs only for diffable targets; pure-plumbing
    # carry grads stay internal
    out_tgt_list = [r for r in tgt_list if r in targets]
    outer_out = []
    for r in out_tgt_list:
        if r in pending and pending[r]:
            gname = "%s@RENAME@%d" % (grad_name_for(r),
                                      len(pending[r]))
        else:
            gname = grad_name_for(r)
        _ensure_grad_var(block, r, gname)
        pending.setdefault(r, []).append(gname)
        grad_to_var[gname] = r
        outer_out.append(gname)

    gop = framework.Operator(
        block, "while_grad",
        {"OutGrads": [incoming.get(w, "@EMPTY@") for w in seeded]},
        {"InGrads": outer_out},
        {"sub_block": gblock, "fwd_block": sub,
         "snap_var": "@WHILE_SNAPS@%d" % (op._id or 0),
         "written": seeded,
         "seed_names": [seed_names[w] for w in seeded],
         "targets": tgt_list,
         "inner_grads": [inner_grads[r] for r in tgt_list],
         "out_targets": out_tgt_list,
         "carries": carries})
    gop._id = program._next_op_id()
    block.ops.append(gop)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """fluid.gradients (reference backward.py:1678): d(targets)/d(inputs)."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    assert len(targets) == 1, "multi-target gradients arrive with a later wave"
    loss = targets[0]
    block = loss.block
    pre_names = {v.name for v in inputs}
    append_backward(loss, parameter_list=[v.name for v in inputs]
                    if all(isinstance(v, framework.Variable) for v in inputs)
                    else None,
                    no_grad_set=no_grad_set)
    outs = []
    for v in inputs:
        gname = framework.grad_var_name(v.name)
        outs.append(block.var(gname) if block.has_var(gname) else None)
    return outs


def _op_info(op_type):
    try:
        return OpInfoMap.instance().get(op_type)
    except KeyError:
        return None


def _has_grad_op(op_type):
    if OpInfoMap.instance().has(op_type + "_grad"):
        return True
    # grad programs are differentiable too: auto-VJP grad ops get their
    # own grad op registered on demand (static double-grad — reference
    # conv2d_grad_grad / elementwise_*_grad_grad)
    return ensure_grad_op(op_type)


def _dtype_enum(dtype):
    from .core import dtypes as _dt

    return _dt.dtype_to_enum(dtype)
