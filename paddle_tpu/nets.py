"""Composite network blocks.

Parity: /root/reference/python/paddle/fluid/nets.py (simple_img_conv_pool,
img_conv_group, sequence_conv_pool, glu, scaled_dot_product_attention) —
the building blocks the book models and user code compose; each is a
pure layer composition, so the TPU story is whatever XLA makes of the
underlying ops (convs and matmuls fuse with their elementwise tails).
"""
from __future__ import annotations

from . import layers

__all__ = ["simple_img_conv_pool", "img_conv_group",
           "sequence_conv_pool", "glu", "scaled_dot_product_attention"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1,
                         conv_padding=0, conv_dilation=1, conv_groups=1,
                         param_attr=None, bias_attr=None, act=None,
                         use_cudnn=True):
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act)
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """Stacked conv(+BN+dropout) group followed by one pool — the VGG
    block (reference nets.py img_conv_group)."""
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def expand(v):
        if not hasattr(v, "__len__"):
            return [v] * len(conv_num_filter)
        assert len(v) == len(conv_num_filter)
        return list(v)

    conv_padding = expand(conv_padding)
    conv_filter_size = expand(conv_filter_size)
    param_attr = expand(param_attr)
    conv_with_batchnorm = expand(conv_with_batchnorm)
    conv_batchnorm_drop_rate = expand(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(
            input=tmp, num_filters=conv_num_filter[i],
            filter_size=conv_filter_size[i], padding=conv_padding[i],
            param_attr=param_attr[i], act=local_conv_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rate)
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None):
    conv_out = layers.sequence_conv(input=input, num_filters=num_filters,
                                    filter_size=filter_size,
                                    param_attr=param_attr, act=act,
                                    bias_attr=bias_attr)
    return layers.sequence_pool(input=conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    """Gated linear unit: split in two along dim, a * sigmoid(b)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled dot-product attention over dense [B, L, D]
    tensors (reference nets.py:scaled_dot_product_attention). Heads
    split/merge via reshape+transpose; the QK^T softmax V core is the
    MXU-friendly batched-matmul XLA path."""
    if len(queries.shape) != 3 or len(keys.shape) != 3 or \
            len(values.shape) != 3:
        raise ValueError("inputs must be 3-D [batch, len, dim]")
    d_model = int(queries.shape[-1])
    if d_model % num_heads != 0:
        raise ValueError("hidden size %d not divisible by num_heads %d"
                         % (d_model, num_heads))

    def split_heads(x):
        if num_heads == 1:
            return x
        b, l = x.shape[0], x.shape[1]
        reshaped = layers.reshape(
            x, shape=[int(b), int(l), num_heads, d_model // num_heads])
        return layers.transpose(reshaped, perm=[0, 2, 1, 3])

    def merge_heads(x):
        if num_heads == 1:
            return x
        t = layers.transpose(x, perm=[0, 2, 1, 3])
        return layers.reshape(
            t, shape=[int(t.shape[0]), int(t.shape[1]),
                      int(t.shape[2]) * int(t.shape[3])])

    q, k, v = split_heads(queries), split_heads(keys), split_heads(values)
    key_dim = float(d_model // num_heads)
    scaled_q = layers.scale(q, scale=key_dim ** -0.5)
    product = layers.matmul(scaled_q, k, transpose_y=True)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate,
                                 is_test=False)
    ctx = layers.matmul(weights, v)
    return merge_heads(ctx)
