"""DecodeEngine: the continuous-batching serving loop.

One step thread owns the cache, the model, and the scheduler; clients
talk to it through ``submit()`` which returns a ``DecodeStream`` —
an iterator of token events fed from the step thread through a queue.
Every token step the thread: (1) runs the scheduler's prefill chunks
(token-budgeted, so long prompts interleave with running decodes),
(2) evicts lowest-priority sequences if the KV arena can't cover the
step (``serving.preemptions``, flight event, re-prefill on
re-admission), (3) runs one batched decode step at a ladder bucket and
fans the new tokens out to their streams.

SLO axis: ``serving.ttft_ms`` (submit -> first token) and
``serving.itl_ms`` (gap between tokens) — the decode-tier replacements
for the one-shot tier's ``serving.queue_ms``; ``GET /metrics`` exports
them like every other family.

Exactly-once streaming: tokens are indexed from 0 and the engine keeps
a bounded LRU of FINISHED streams' tokens, so a duplicate submit
(hedge, retry) replays instantly from any ``resume_from`` index, and a
submit that arrives while the original is still in flight attaches as
a second subscriber to the SAME sequence — both see every event, each
filtered to its own resume index. A resumed stream on a FRESH replica
(fleet failover) has no LRU entry; it regenerates from the prompt —
deterministic greedy decode makes the regenerated tokens bit-identical
(``model.py``) — and suppresses emission below ``resume_from``. Either
way the client never sees a token index twice: that is what lets the
fleet hedge and fail over decode streams with the same exactly-once
latch it uses for one-shot requests.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ...observability import flight
from .. import metrics as M
from ..batcher import default_ladder, pick_bucket
from ..engine import (DeadlineExpired, EngineStopped, RequestTooLarge,
                      ServerOverloaded, ServingError)
from .kvcache import KVCacheConfig, PagedKVCache
from .model import TinyDecodeLM
from .scheduler import DecodeScheduler, SeqState

__all__ = ["DecodeConfig", "DecodeEngine", "DecodeStream"]

# decode cost classes mirror the fleet's admission lanes (highest
# priority first); rank here = shed/evict order there
_CLASS_RANK = {"high": 0, "normal": 1, "low": 2}


class DecodeConfig:
    """Engine knobs. ``kv_*`` shape the cache arena; ``ladder`` is the
    decode batch buckets (None -> powers of two up to
    ``max_batch_size``); ``prefill_chunk_tokens`` is the per-step
    prompt budget; ``max_tokens_cap`` bounds any single stream;
    ``default_deadline_s`` applies when a submit names none
    (None -> no deadline)."""

    def __init__(self, *,
                 kv_blocks: int = 128,
                 kv_block_tokens: int = 16,
                 kv_dtype: str = "f32",
                 num_layers: int = 2,
                 num_heads: int = 2,
                 head_dim: int = 8,
                 vocab_size: int = 97,
                 model_seed: int = 0xD0DE,
                 max_batch_size: int = 8,
                 ladder: Optional[Tuple[int, ...]] = None,
                 prefill_chunk_tokens: int = 32,
                 max_waiting: int = 64,
                 default_max_tokens: int = 16,
                 max_tokens_cap: int = 512,
                 max_prompt_tokens: int = 1024,
                 default_deadline_s: Optional[float] = None,
                 dedup_capacity: int = 256,
                 attn_backend: Optional[str] = None,
                 eos_token: Optional[int] = 0,
                 step_idle_s: float = 0.05):
        self.cache = KVCacheConfig(
            num_blocks=kv_blocks, block_tokens=kv_block_tokens,
            num_layers=num_layers, num_heads=num_heads,
            head_dim=head_dim, dtype=kv_dtype)
        self.vocab_size = int(vocab_size)
        self.model_seed = int(model_seed)
        self.max_batch_size = int(max_batch_size)
        self.ladder = tuple(ladder) if ladder else default_ladder(
            self.max_batch_size)
        self.prefill_chunk_tokens = int(prefill_chunk_tokens)
        self.max_waiting = int(max_waiting)
        self.default_max_tokens = int(default_max_tokens)
        self.max_tokens_cap = int(max_tokens_cap)
        self.max_prompt_tokens = int(max_prompt_tokens)
        self.default_deadline_s = default_deadline_s
        self.dedup_capacity = int(dedup_capacity)
        self.attn_backend = attn_backend
        self.eos_token = eos_token
        self.step_idle_s = float(step_idle_s)


class DecodeStream:
    """Client handle: iterate token events, or drain with
    ``result()``. Events are dicts:

    ``{"type": "token", "index": i, "token": t}`` then one terminal
    ``{"type": "finish", "reason": r, "tokens": n}`` where reason is
    ``eos | max_tokens | deadline_expired | cancelled |
    engine_stopped``. Error reasons also carry ``"error": message``.
    Iteration ends after the finish event."""

    def __init__(self, request_id: str, resume_from: int = 0):
        self.request_id = request_id
        self.resume_from = int(resume_from)
        self._q: "queue.Queue[Optional[dict]]" = queue.Queue()
        self._cancel = threading.Event()
        self.finish: Optional[dict] = None

    # engine side -----------------------------------------------------------

    def _push(self, event: dict) -> None:
        if event.get("type") == "finish":
            self.finish = event
        self._q.put(event)

    def cancelled(self) -> bool:
        return self._cancel.is_set()

    # client side -----------------------------------------------------------

    def cancel(self) -> None:
        """Ask the engine to stop this stream; a terminal finish event
        (reason ``cancelled``) still arrives."""
        self._cancel.set()

    def __iter__(self) -> Iterator[dict]:
        while True:
            ev = self._q.get()
            yield ev
            if ev.get("type") == "finish":
                return

    def result(self, timeout_s: Optional[float] = None
               ) -> Tuple[List[int], dict]:
        """Drain: ``(tokens in index order, finish event)``. Raises
        the stream's terminal error as a typed ServingError."""
        deadline = (time.monotonic() + timeout_s) if timeout_s else None
        toks: Dict[int, int] = {}
        while True:
            left = None if deadline is None else max(
                0.0, deadline - time.monotonic())
            try:
                ev = self._q.get(timeout=left)
            except queue.Empty:
                raise TimeoutError("stream %r: no event within %.1fs"
                                   % (self.request_id, timeout_s))
            if ev["type"] == "token":
                toks[ev["index"]] = ev["token"]
            elif ev["type"] == "finish":
                if ev["reason"] == "deadline_expired":
                    raise DeadlineExpired(ev.get("error", ev["reason"]))
                if ev["reason"] == "engine_stopped":
                    raise EngineStopped(ev.get("error", ev["reason"]))
                return ([toks[i] for i in sorted(toks)], ev)


class _Entry:
    """One live sequence: scheduler state + stream fan-out."""

    __slots__ = ("seq", "request_id", "max_tokens", "deadline",
                 "submit_t", "first_token_t", "last_token_t", "subs")

    def __init__(self, seq: SeqState, request_id: str, max_tokens: int,
                 deadline: Optional[float]):
        self.seq = seq
        self.request_id = request_id
        self.max_tokens = max_tokens
        self.deadline = deadline
        self.submit_t = time.monotonic()
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        self.subs: List[DecodeStream] = []


class DecodeEngine:
    """See module docstring. Lifecycle mirrors ``ServingEngine``:
    ``start() -> serving``, ``stop(drain=True)`` finishes resident
    streams first; ``health()`` reports the same phase strings so the
    fleet prober needs no special casing."""

    def __init__(self, config: Optional[DecodeConfig] = None):
        self.config = config or DecodeConfig()
        self.cache = PagedKVCache(self.config.cache)
        self.model = TinyDecodeLM(
            self.cache, vocab_size=self.config.vocab_size,
            seed=self.config.model_seed,
            attn_backend=self.config.attn_backend,
            eos_token=self.config.eos_token)
        self.scheduler = DecodeScheduler(
            self.cache, self.config.ladder,
            prefill_chunk_tokens=self.config.prefill_chunk_tokens,
            max_running=self.config.max_batch_size)
        self._phase = "starting"
        # ONE reentrant lock over entries + scheduler + cache: the
        # step thread holds it for a whole token step (compute is
        # milliseconds at this scale), so submit/stats/step never
        # interleave mid-mutation. Reentrant because _finish runs both
        # from submit (lock held once) and from inside a step.
        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        self._entries: Dict[str, _Entry] = {}      # request_id -> live
        self._finished: "OrderedDict[str, dict]" = OrderedDict()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._seq_counter = 0
        self.steps = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "DecodeEngine":
        if self._thread is not None:
            raise RuntimeError("engine already started")
        self._phase = "serving"
        self._thread = threading.Thread(
            target=self._step_loop, name="decode-engine", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True,
             timeout_s: float = 30.0) -> None:
        with self._lock:
            if self._phase == "stopped":
                return
            self._phase = "draining"
            self._wake.notify_all()
        if drain:
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._entries:
                        break
                time.sleep(0.01)
        self._stop.set()
        with self._lock:
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
        with self._lock:
            for entry in list(self._entries.values()):
                self._finish(entry, "engine_stopped",
                             error="engine stopped before completion")
            self._phase = "stopped"

    def health(self) -> str:
        return self._phase

    def health_doc(self) -> dict:
        """The /healthz body: lifecycle phase plus what a decode
        prober needs — ``engine_kind`` so routers stop schema-sniffing
        and the KV occupancy that decides where new streams fit."""
        kv = self.cache.stats()
        with self._lock:
            live = len(self._entries)
        return {
            "status": self._phase,
            "engine_kind": "decode",
            "kv_occupancy": kv["occupancy"],
            "kv_free_blocks": kv["free_blocks"],
            "kv_blocks": kv["num_blocks"],
            "kv_dtype": kv["dtype"],
            "active_streams": live,
            "steps": self.steps,
        }

    def stats(self) -> dict:
        out = M.snapshot()
        out["kv"] = self.cache.stats()
        out["steps"] = self.steps
        return out

    # -- submit -------------------------------------------------------------

    def submit(self, prompt, *, max_tokens: Optional[int] = None,
               request_id: Optional[str] = None,
               cost_class: str = "high",
               deadline_s: Optional[float] = None,
               resume_from: int = 0) -> DecodeStream:
        """Start (or attach to, or replay) a decode stream.

        ``prompt`` is a non-empty list of token ids < vocab_size;
        ``resume_from`` suppresses emission of token indices below it
        (fleet failover/hedge — the tokens are regenerated or
        replayed, never re-delivered). Raises ``ServerOverloaded`` /
        ``RequestTooLarge`` / ``EngineStopped`` synchronously, like
        the one-shot engine."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ServingError("empty prompt")
        if any(t < 0 or t >= self.config.vocab_size for t in prompt):
            raise ServingError("prompt token out of range [0, %d)"
                               % self.config.vocab_size)
        if len(prompt) > self.config.max_prompt_tokens:
            raise RequestTooLarge(
                "prompt of %d tokens exceeds max_prompt_tokens=%d"
                % (len(prompt), self.config.max_prompt_tokens))
        n_max = int(max_tokens or self.config.default_max_tokens)
        if n_max < 1:
            raise ServingError("max_tokens must be >= 1")
        n_max = min(n_max, self.config.max_tokens_cap)
        if cost_class not in _CLASS_RANK:
            raise ServingError("unknown cost class %r (have %s)"
                               % (cost_class, sorted(_CLASS_RANK)))
        resume_from = max(0, int(resume_from))
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        rid = request_id or ("stream-%d-%d"
                             % (id(self), time.monotonic_ns()))

        with self._lock:
            if self._phase not in ("serving", "warming"):
                raise EngineStopped("engine is %s" % self._phase)
            stream = DecodeStream(rid, resume_from)
            # replay a finished stream from the LRU (hedge/failover
            # landing after completion): exactly-once by construction
            done = self._finished.get(rid)
            if done is not None:
                self._finished.move_to_end(rid)
                M.inc(M.DEDUP_HITS)
                for i, t in enumerate(done["tokens"]):
                    if i >= resume_from:
                        stream._push({"type": "token", "index": i,
                                      "token": t})
                stream._push(dict(done["finish"]))
                return stream
            live = self._entries.get(rid)
            if live is not None:
                # in-flight duplicate: second subscriber, same sequence
                M.inc(M.DEDUP_HITS)
                for i, t in enumerate(live.seq.generated):
                    if i >= resume_from:
                        stream._push({"type": "token", "index": i,
                                      "token": t})
                live.subs.append(stream)
                return stream
            if self.scheduler.depth() >= self.config.max_waiting:
                M.inc(M.REJECTED)
                raise ServerOverloaded(
                    "%d streams resident (max_waiting=%d)"
                    % (self.scheduler.depth(), self.config.max_waiting))
            self._seq_counter += 1
            seq = SeqState("seq-%d" % self._seq_counter, prompt,
                           _CLASS_RANK[cost_class],
                           self.scheduler.next_arrival())
            entry = _Entry(seq, rid, n_max,
                           (time.monotonic() + deadline_s)
                           if deadline_s else None)
            entry.subs.append(stream)
            self._entries[rid] = entry
            self.cache.register(seq.seq_id)
            self.scheduler.add(seq)
            M.inc(M.STREAMS)
            if resume_from > 0:
                M.inc(M.STREAM_RESUMES)
            self._wake.notify_all()
        return stream

    def generate(self, prompt, *, max_tokens: Optional[int] = None,
                 request_id: Optional[str] = None,
                 cost_class: str = "high",
                 deadline_s: Optional[float] = None,
                 resume_from: int = 0) -> DecodeStream:
        """The streaming-surface name ``http.py`` and the fleet route
        by (an engine with ``.generate`` streams; one without is
        one-shot). Same contract as ``submit``."""
        return self.submit(prompt, max_tokens=max_tokens,
                           request_id=request_id, cost_class=cost_class,
                           deadline_s=deadline_s,
                           resume_from=resume_from)

    # -- step loop ----------------------------------------------------------

    def _step_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                plan = self.scheduler.plan()
                if plan.empty():
                    self._wake.wait(timeout=self.config.step_idle_s)
                    continue
                entries = {e.seq.seq_id: e
                           for e in self._entries.values()}
                try:
                    self._run_step(plan, entries)
                except Exception as exc:  # pragma: no cover
                    # a step-loop crash would silently hang every
                    # stream; fail them loudly instead
                    for entry in list(self._entries.values()):
                        self._finish(entry, "engine_stopped",
                                     error="step loop error: %s" % exc)
                    self._phase = "stopped"
                    raise
                self.steps += 1
                M.set_gauge(M.KV_OCCUPANCY, self.cache.occupancy())

    def _run_step(self, plan, entries: Dict[str, _Entry]) -> None:
        now = time.monotonic()
        # 0) deadlines + cancels reap before any compute
        for seq in list(self.scheduler.sequences()):
            entry = entries.get(seq.seq_id)
            if entry is None:
                continue
            if any(s.cancelled() for s in entry.subs):
                with self._lock:
                    self._finish(entry, "cancelled")
            elif entry.deadline is not None and now > entry.deadline:
                with self._lock:
                    self._finish(entry, "deadline_expired",
                                 error="deadline passed after %d token(s)"
                                 % len(seq.generated))

        # 1) prefill chunks under the token budget
        for seq, take in plan.prefill:
            entry = entries.get(seq.seq_id)
            if entry is None or seq.phase != "waiting":
                continue
            tokens = seq.replay()[seq.prefilled:seq.prefilled + take]
            if not self._ensure_fit(seq, len(tokens), entries):
                continue                      # defer; try next step
            if not self.cache.has(seq.seq_id):
                self.cache.register(seq.seq_id)
            h = self.model.prefill_chunk(seq.seq_id, tokens)
            seq.prefilled += len(tokens)
            seq.phase = "prefill"
            M.inc(M.PREFILL_TOKENS, len(tokens))
            if seq.prefilled == len(seq.replay()):
                # prompt (+ any pre-preemption tokens) fully resident:
                # the chunk's last hidden row yields the next token
                nxt = int(np.argmax(self.model.logits1(
                    h, seq.prefilled)))
                self.scheduler.promote(seq)
                self._emit(entry, nxt)
            else:
                seq.phase = "waiting"

        # 2) decode step over the running set at a ladder bucket
        batch = [s for s in plan.decode
                 if s.phase == "running"
                 and entries.get(s.seq_id) is not None]
        if not batch:
            return
        # memory pressure: every member needs one token's worth of
        # blocks; evict lowest-priority residents (possibly batch
        # members) until the step fits
        need = sum(self.cache.blocks_needed(s.seq_id, 1) for s in batch)
        while need > self.cache.free_blocks():
            victim = self._preempt_one(batch[0], entries)
            if victim is None:
                break
            if victim in batch:
                batch.remove(victim)
            if not batch:
                return
            need = sum(self.cache.blocks_needed(s.seq_id, 1)
                       for s in batch)
        if need > self.cache.free_blocks():
            return                             # arena pinned; wait
        ids = [s.seq_id for s in batch]
        last = [s.last_token for s in batch]
        bucket = pick_bucket(self.config.ladder, len(batch))
        M.observe(M.DECODE_BATCH, len(batch))
        M.inc(M.DECODE_STEPS)
        _, nxt = self.model.decode_step(ids, last, pad_to=bucket)
        for s, t in zip(batch, nxt):
            entry = entries.get(s.seq_id)
            if entry is not None:
                self._emit(entry, int(t))

    def _ensure_fit(self, seq: SeqState, n_tokens: int,
                    entries: Dict[str, _Entry]) -> bool:
        """Evict strictly-lower-priority residents until ``seq`` can
        take ``n_tokens``; False -> could not make room, defer."""
        while not self.cache.can_fit(
                seq.seq_id if self.cache.has(seq.seq_id) else None,
                n_tokens):
            needed = self.cache.blocks_needed(
                seq.seq_id if self.cache.has(seq.seq_id) else None,
                n_tokens) - self.cache.free_blocks()
            victims = self.scheduler.pick_victims(needed, seq)
            if not victims:
                return False
            for v in victims:
                self._do_preempt(v, entries)
        return True

    def _preempt_one(self, requester: SeqState,
                     entries: Dict[str, _Entry]) -> Optional[SeqState]:
        victims = self.scheduler.pick_victims(1, requester)
        if not victims:
            return None
        self._do_preempt(victims[0], entries)
        return victims[0]

    def _do_preempt(self, victim: SeqState,
                    entries: Dict[str, _Entry]) -> None:
        freed = self.scheduler.preempt(victim)
        M.inc(M.PREEMPTIONS)
        flight.record("serving.kv_preempt", seq=victim.seq_id,
                      blocks_freed=freed,
                      generated=len(victim.generated),
                      priority=victim.priority,
                      preemptions=victim.preemptions)

    def _emit(self, entry: _Entry, token: int) -> None:
        """Record one generated token, fan out to subscribers, close
        the stream when a finish condition hits."""
        seq = entry.seq
        index = len(seq.generated)
        seq.generated.append(token)
        seq.last_token = token
        now = time.monotonic()
        if entry.first_token_t is None:
            entry.first_token_t = now
            M.observe(M.TTFT_MS, (now - entry.submit_t) * 1e3)
        elif entry.last_token_t is not None:
            M.observe(M.ITL_MS, (now - entry.last_token_t) * 1e3)
        entry.last_token_t = now
        M.inc(M.TOKENS)
        for sub in entry.subs:
            if index >= sub.resume_from:
                sub._push({"type": "token", "index": index,
                           "token": token})
        if self.model.eos_token is not None and \
                token == self.model.eos_token:
            with self._lock:
                self._finish(entry, "eos")
        elif len(seq.generated) >= entry.max_tokens:
            with self._lock:
                self._finish(entry, "max_tokens")

    def _finish(self, entry: _Entry, reason: str,
                error: Optional[str] = None) -> None:
        """Terminal transition (caller holds the lock): release cache,
        drop from scheduler, push the finish event, remember the
        stream in the dedup LRU."""
        if self._entries.get(entry.request_id) is not entry:
            return                               # already finished
        del self._entries[entry.request_id]
        self.scheduler.remove(entry.seq)
        self.cache.release(entry.seq.seq_id)
        ev = {"type": "finish", "reason": reason,
              "tokens": len(entry.seq.generated),
              "preemptions": entry.seq.preemptions}
        if error is not None:
            ev["error"] = error
            M.inc(M.STREAM_ERRORS)
        for sub in entry.subs:
            sub._push(dict(ev))
        self._finished[entry.request_id] = {
            "tokens": list(entry.seq.generated), "finish": ev}
        while len(self._finished) > self.config.dedup_capacity:
            self._finished.popitem(last=False)
