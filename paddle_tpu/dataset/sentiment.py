"""Movie-review sentiment reader creators (reference
python/paddle/dataset/sentiment.py — NLTK movie_reviews based).

Sample contract: (word_ids, label 0/1). Offline: reuses the imdb
synthetic grammar with the sentiment module's API (get_word_dict,
train, test).
"""
from __future__ import annotations

from . import imdb

__all__ = ["get_word_dict", "train", "test"]

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000

_word_dict = None


def get_word_dict():
    global _word_dict
    if _word_dict is None:
        _word_dict = imdb.build_dict()
    return _word_dict


def train():
    wd = get_word_dict()
    return imdb._reader_creator(wd, True, NUM_TRAINING_INSTANCES, seed=23)


def test():
    wd = get_word_dict()
    return imdb._reader_creator(
        wd, False, NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES, seed=24)
