"""AsyncExecutor + DataFeedDesc.

Parity: /root/reference/python/paddle/fluid/async_executor.py
(AsyncExecutor :63 — the legacy file-driven async PS trainer driver)
and data_feed_desc.py (DataFeedDesc over the paddle.framework.DataFeedDesc
prototext).

TPU-native stance: the reference drives C++ ExecutorThreadWorker
threads over DataFeed files with no Python in the loop; here the same
contract routes through fluid.dataset's native-C++/numpy multi-slot
readers into Executor.run steps (each a compiled whole-program
dispatch). The class is kept because user scripts construct it; new
code should prefer Executor.train_from_dataset directly, mirroring the
reference's own deprecation path.
"""
from __future__ import annotations

import re
from typing import List, Optional

from . import framework
from .executor import Executor, global_scope


class DataFeedDesc:
    """Parse the reference's MultiSlotDataFeed prototext into slot
    metadata (data_feed_desc.py contract: set_batch_size,
    set_dense_slots, set_use_slots, desc())."""

    def __init__(self, proto_file_path: str):
        with open(proto_file_path) as f:
            self._text = f.read()
        self.batch_size = 1
        m = re.search(r"batch_size\s*:\s*(\d+)", self._text)
        if m:
            self.batch_size = int(m.group(1))
        # top-level text only (slot blocks stripped) — a slot's name
        # must not be mistaken for the feed name
        body = re.sub(r"multi_slot_desc\s*\{.*\}", "", self._text,
                      flags=re.S)
        m = re.search(r'name\s*:\s*"([^"]+)"', body)
        self.name = m.group(1) if m else "MultiSlotDataFeed"
        # top-level fields we don't model (pipe_command etc.) survive
        # the desc() round-trip verbatim
        self._extra_lines = [
            ln.strip() for ln in body.splitlines()
            if ln.strip() and not re.match(
                r'(name|batch_size)\s*:', ln.strip())]
        # slots: name/type/is_dense/is_used blocks in declaration order
        self.slots = []
        for block in re.findall(r"slots\s*\{([^}]*)\}", self._text):
            name = re.search(r'name\s*:\s*"([^"]+)"', block)
            stype = re.search(r'type\s*:\s*"([^"]+)"', block)
            dense = re.search(r"is_dense\s*:\s*(\w+)", block)
            used = re.search(r"is_used\s*:\s*(\w+)", block)
            self.slots.append({
                "name": name.group(1) if name else "",
                "type": stype.group(1) if stype else "uint64",
                "is_dense": bool(dense and dense.group(1) == "true"),
                "is_used": bool(used and used.group(1) == "true"),
            })
        self._slot_by_name = {s["name"]: s for s in self.slots}

    def set_batch_size(self, batch_size: int):
        self.batch_size = int(batch_size)

    def set_dense_slots(self, dense_slots_name: List[str]):
        for n in dense_slots_name:
            self._slot_by_name[n]["is_dense"] = True

    def set_use_slots(self, use_slots_name: List[str]):
        for n in use_slots_name:
            self._slot_by_name[n]["is_used"] = True

    def desc(self) -> str:
        """Regenerate the prototext from current state (the reference
        rebuilds from its proto, so setters are reflected)."""
        lines = ['name: "%s"' % self.name,
                 "batch_size: %d" % self.batch_size]
        lines += self._extra_lines
        lines.append("multi_slot_desc {")
        for s in self.slots:
            lines += ["  slots {",
                      '    name: "%s"' % s["name"],
                      '    type: "%s"' % s["type"],
                      "    is_dense: %s" % str(s["is_dense"]).lower(),
                      "    is_used: %s" % str(s["is_used"]).lower(),
                      "  }"]
        lines.append("}")
        return "\n".join(lines) + "\n"


class AsyncExecutor:
    """(reference async_executor.py:63). ``run`` trains a program over a
    filelist with a multi-slot feed — thread_num maps to reader threads
    (the compute itself is one compiled program per step)."""

    def __init__(self, place=None, run_mode=""):
        from .core.place import CPUPlace

        self.place = place if place is not None else CPUPlace()
        self.executor = Executor(self.place)

    def run(self, program, data_feed, filelist, thread_num=1, fetch=None,
            mode="", debug=False, scope=None):
        from .dataset_module import DatasetFactory

        program = program or framework.default_main_program()
        scope = scope or global_scope()
        if isinstance(filelist, str):
            filelist = [filelist]
        block = program.global_block()

        dataset = DatasetFactory().create_dataset("QueueDataset")
        if isinstance(data_feed, DataFeedDesc):
            dataset.set_batch_size(data_feed.batch_size)
            use_vars = [block.var(s["name"]) for s in data_feed.slots
                        if s["is_used"]]
        else:  # an already-configured fluid.dataset object
            return self.executor.train_from_dataset(
                program=program, dataset=data_feed, scope=scope,
                thread=thread_num, fetch_list=fetch, debug=debug)
        dataset.set_use_var(use_vars)
        dataset.set_thread(thread_num)
        dataset.set_filelist(filelist)
        return self.executor.train_from_dataset(
            program=program, dataset=dataset, scope=scope,
            thread=thread_num, fetch_list=fetch, debug=debug)
