"""CoNLL-2005 SRL reader creators (reference
python/paddle/dataset/conll05.py).

Sample contract (reference reader_creator): 9-slot tuple
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_ids, mark, label
_ids) — the 5 context windows around the predicate, the predicate id
broadcast over the sentence, the predicate mark, and per-token BIO
label ids. Synthetic fallback: template sentences with one verb and
B-A0/B-A1 arguments, deterministic.
"""
from __future__ import annotations

import os

import numpy as np

from .common import DATA_HOME

__all__ = ["get_dict", "get_embedding", "test"]

_WORDS = ["the", "cat", "dog", "man", "woman", "ball", "saw", "hit",
          "gave", "took", "red", "big", "park", "home"]
_VERBS = ["saw", "hit", "gave", "took"]
_LABELS = ["O", "B-A0", "I-A0", "B-A1", "I-A1", "B-V"]


def get_dict():
    """(word_dict, verb_dict, label_dict)."""
    word_dict = {w: i for i, w in enumerate(_WORDS)}
    word_dict["<unk>"] = len(word_dict)
    verb_dict = {v: i for i, v in enumerate(_VERBS)}
    label_dict = {l: i for i, l in enumerate(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Deterministic stand-in for the downloaded emb file."""
    word_dict, _, _ = get_dict()
    rng = np.random.RandomState(99)
    return rng.rand(len(word_dict), 32).astype("float32")


def _synthetic_sentences(n, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        subj = _WORDS[rng.randint(0, 6)]
        verb = _VERBS[rng.randint(0, len(_VERBS))]
        obj = _WORDS[rng.randint(0, 6)]
        words = ["the", subj, verb, "the", obj]
        labels = ["B-A0", "I-A0", "B-V", "B-A1", "I-A1"]
        yield words, verb, 2, labels


def reader_creator(n=200, seed=80):
    word_dict, verb_dict, label_dict = get_dict()
    unk = word_dict["<unk>"]

    def reader():
        for words, verb, vidx, labels in _synthetic_sentences(n, seed):
            ids = [word_dict.get(w, unk) for w in words]
            L = len(ids)

            def ctx(off):
                j = vidx + off
                return [ids[j] if 0 <= j < L else unk] * L

            verb_ids = [verb_dict[verb]] * L
            mark = [1 if i == vidx else 0 for i in range(L)]
            label_ids = [label_dict[l] for l in labels]
            yield (ids, ctx(-2), ctx(-1), ctx(0), ctx(1), ctx(2),
                   verb_ids, mark, label_ids)

    return reader


def test():
    d = os.path.join(DATA_HOME, "conll05st")
    if os.path.exists(os.path.join(d, "conll05st-tests.tar.gz")):
        raise NotImplementedError(
            "real conll05 archive parsing is not supported offline; "
            "remove %s to use the synthetic reader" % d)
    return reader_creator(200, seed=80)
