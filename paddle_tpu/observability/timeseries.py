"""Windowed time-series telemetry for the self-driving runtime.

The steering loop (ISSUE 16/18) judged everything over LIFETIME
counter ratios — ``serving.padding_waste / serving.batches`` since
process start — so a regression in the last minute drowns in hours of
good history, and a canary comparison inherits whatever drift happened
while the counters accumulated. This module keeps a bounded per-metric
ring of ``(wall_ts, value)`` snapshots, sampled on the existing
periodic-dump tick, so rules and canaries can ask for the **delta /
rate over the last window** instead.

Design rules (same contract as ``capture.py``):

- Armed by ``PADDLE_TPU_METRICS_DIR`` (the same knob that arms dumps);
  ``PADDLE_TPU_TIMESERIES=0`` force-disables sampling even when dumps
  are on. Both knobs are memoized — the disabled path is one memoized
  load + branch, under the gate-4 <1us budget
  (``paddle_tpu.tools.obs_overhead`` asserts it).
- The ring is bounded (``PADDLE_TPU_TIMESERIES_WINDOWS``, default 64
  points per series) so a week-long job holds kilobytes, not history.
- Counters are stored as sampled ABSOLUTE values; windowed deltas are
  computed per adjacent hop and clamped at 0, so a counter reset
  across a process relaunch reads as "no progress that hop", never a
  negative rate.
- Histograms ride as two monotone series, ``<qn>#sum`` and
  ``<qn>#count``, so a windowed mean is ``delta(sum)/delta(count)``.

Per-process series ride the dump files (``distributed.dump_process``
attaches ``doc["series"]``) and ``merge_job_dir`` folds them into the
job ``metrics.json``: per-rank series plus job-aligned windows
(``series_windows``) rebased with the PR-10 applied clock-skew
correction so "the last window" means the same wall interval on every
rank.
"""
from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

SERIES_ENV = "PADDLE_TPU_TIMESERIES"
WINDOWS_ENV = "PADDLE_TPU_TIMESERIES_WINDOWS"
ARM_ENV = "PADDLE_TPU_METRICS_DIR"
DEFAULT_WINDOWS = 64

_OFF_VALUES = ("0", "off", "false", "no")

# memoized knobs: None = unread. Read-mostly after first touch; the
# lock only guards the (rare) mutation of the series store itself.
_ENABLED: Optional[bool] = None
_CAP: Optional[int] = None
_lock = threading.Lock()
# qualified metric name -> {"kind": "counter"|"gauge",
#                           "points": deque[(wall_ts, value)]}
_store: Dict[str, Dict[str, Any]] = {}


def series_enabled() -> bool:
    """True iff sampling is armed: dumps are on (metrics dir set) and
    ``PADDLE_TPU_TIMESERIES`` does not force it off. Memoized."""
    global _ENABLED
    if _ENABLED is None:
        raw = os.environ.get(SERIES_ENV, "").strip().lower()
        if raw in _OFF_VALUES and raw != "":
            _ENABLED = False
        else:
            _ENABLED = bool(os.environ.get(ARM_ENV))
    return _ENABLED


def window_cap() -> int:
    """Ring bound: points kept per series. Memoized; min 2 (a delta
    needs two samples)."""
    global _CAP
    if _CAP is None:
        try:
            _CAP = int(os.environ.get(WINDOWS_ENV, "") or DEFAULT_WINDOWS)
        except ValueError:
            _CAP = DEFAULT_WINDOWS
        if _CAP < 2:
            _CAP = 2
    return _CAP


def _reset_for_tests() -> None:
    global _ENABLED, _CAP
    with _lock:
        _ENABLED = None
        _CAP = None
        _store.clear()


def _append_locked(name: str, kind: str, ts: float, value: float) -> None:
    ser = _store.get(name)
    if ser is None:
        ser = {"kind": kind, "points": deque(maxlen=window_cap())}
        _store[name] = ser
    ser["points"].append((ts, value))


def record_point(name: str, value: Any, wall_ts: Optional[float] = None,
                 kind: str = "gauge") -> None:
    """Record one sample of one series. Safe to call unconditionally:
    no-op (memoized branch) when sampling is off or the value is not
    numeric."""
    if not series_enabled():
        return
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        return
    import time

    ts = float(wall_ts) if wall_ts is not None else time.time()
    with _lock:
        _append_locked(name, kind, ts, float(value))


def record_samples(snapshot: Optional[Dict[str, Any]],
                   wall_ts: Optional[float] = None) -> int:
    """Sample every metric in a registry ``snapshot()`` dict into the
    ring. Called on the periodic-dump tick. Returns the number of
    series touched (0 when disabled or the snapshot is unusable)."""
    if not series_enabled():
        return 0
    if not isinstance(snapshot, dict):
        return 0
    import time

    ts = float(wall_ts) if wall_ts is not None else time.time()
    touched = 0
    counters = snapshot.get("counters") or {}
    gauges = snapshot.get("gauges") or {}
    histograms = snapshot.get("histograms") or {}
    with _lock:
        for qn, v in counters.items():
            if isinstance(v, (int, float)):
                _append_locked(qn, "counter", ts, float(v))
                touched += 1
        for qn, v in gauges.items():
            if isinstance(v, (int, float)):
                _append_locked(qn, "gauge", ts, float(v))
                touched += 1
        for qn, h in histograms.items():
            if not isinstance(h, dict):
                continue
            s, c = h.get("sum"), h.get("count")
            if isinstance(s, (int, float)) and isinstance(c, (int, float)):
                # monotone pair: windowed mean = delta(sum)/delta(count)
                _append_locked(qn + "#sum", "counter", ts, float(s))
                _append_locked(qn + "#count", "counter", ts, float(c))
                touched += 1
    return touched


def process_series() -> Dict[str, Dict[str, Any]]:
    """JSON-able snapshot of this process's rings:
    ``{qn: {"kind": ..., "points": [[ts, value], ...]}}``. Empty when
    sampling is off or nothing was recorded."""
    if not series_enabled():
        return {}
    out: Dict[str, Dict[str, Any]] = {}
    with _lock:
        for qn, ser in _store.items():
            out[qn] = {"kind": ser["kind"],
                       "points": [[t, v] for (t, v) in ser["points"]]}
    return out


# ---------------------------------------------------------------------------
# pure window queries (operate on a points list; no global state)
# ---------------------------------------------------------------------------

def _window_points(points: Sequence[Sequence[float]],
                   window_s: Optional[float] = None,
                   now: Optional[float] = None
                   ) -> List[Tuple[float, float]]:
    pts = [(float(p[0]), float(p[1])) for p in points
           if isinstance(p, (list, tuple)) and len(p) >= 2]
    pts.sort(key=lambda p: p[0])
    if window_s is None or not pts:
        return pts
    t_hi = float(now) if now is not None else pts[-1][0]
    t_lo = t_hi - float(window_s)
    return [p for p in pts if p[0] >= t_lo]


def counter_delta(points: Sequence[Sequence[float]],
                  window_s: Optional[float] = None,
                  now: Optional[float] = None) -> Optional[float]:
    """Total increase of a sampled monotone counter over the trailing
    window. Each adjacent hop contributes ``max(0, v[i+1]-v[i])`` — a
    drop (counter reset across relaunch) clamps that hop at 0, so the
    delta never goes negative. None with fewer than 2 points."""
    pts = _window_points(points, window_s, now)
    if len(pts) < 2:
        return None
    total = 0.0
    for (_, a), (_, b) in zip(pts, pts[1:]):
        total += max(0.0, b - a)
    return total


def window_span(points: Sequence[Sequence[float]],
                window_s: Optional[float] = None,
                now: Optional[float] = None) -> Optional[float]:
    """Seconds between first and last point in the window; None with
    fewer than 2 points."""
    pts = _window_points(points, window_s, now)
    if len(pts) < 2:
        return None
    return pts[-1][0] - pts[0][0]


def counter_rate(points: Sequence[Sequence[float]],
                 window_s: Optional[float] = None,
                 now: Optional[float] = None) -> Optional[float]:
    """Windowed delta / windowed span (per-second rate); None when the
    delta is undefined or the span is not positive."""
    delta = counter_delta(points, window_s, now)
    span = window_span(points, window_s, now)
    if delta is None or span is None or span <= 0:
        return None
    return delta / span


def last_value(points: Sequence[Sequence[float]]) -> Optional[float]:
    pts = _window_points(points)
    return pts[-1][1] if pts else None


# ---------------------------------------------------------------------------
# job-level fold (used by distributed.merge_job_dir)
# ---------------------------------------------------------------------------

def job_windows(per_proc_series: Dict[str, Dict[str, Dict[str, Any]]],
                skews_us: Optional[Dict[str, float]] = None,
                window_s: Optional[float] = None) -> Dict[str, Any]:
    """Fold per-process series into job-aligned windows. Each rank's
    timestamps are rebased by its APPLIED clock skew (the PR-10
    correction: ``distributed.applied_clock_skew_us``) so a window
    means the same wall interval on every rank. Counter series fold to
    a summed-across-ranks delta + rate with per-rank provenance;
    gauge series fold to per-rank last values."""
    skews_us = skews_us or {}
    out: Dict[str, Any] = {}
    by_metric: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for proc, series in (per_proc_series or {}).items():
        if not isinstance(series, dict):
            continue
        off_s = float(skews_us.get(proc, 0.0) or 0.0) / 1e6
        for qn, ser in series.items():
            if not isinstance(ser, dict):
                continue
            pts = [[float(p[0]) - off_s, float(p[1])]
                   for p in (ser.get("points") or [])
                   if isinstance(p, (list, tuple)) and len(p) >= 2]
            if not pts:
                continue
            slot = by_metric.setdefault(qn, {})
            slot[proc] = {"kind": ser.get("kind", "gauge"), "points": pts}
    for qn, ranks in by_metric.items():
        kinds = {r["kind"] for r in ranks.values()}
        kind = "counter" if kinds == {"counter"} else (
            "gauge" if kinds == {"gauge"} else "mixed")
        if kind == "counter":
            per_rank: Dict[str, Any] = {}
            total = 0.0
            t0: Optional[float] = None
            t1: Optional[float] = None
            for proc, ser in ranks.items():
                d = counter_delta(ser["points"], window_s)
                if d is None:
                    continue
                span = window_span(ser["points"], window_s) or 0.0
                pts = _window_points(ser["points"], window_s)
                per_rank[proc] = {
                    "delta": d,
                    "rate": (d / span) if span > 0 else None,
                    "t0": pts[0][0], "t1": pts[-1][0],
                }
                total += d
                t0 = pts[0][0] if t0 is None else min(t0, pts[0][0])
                t1 = pts[-1][0] if t1 is None else max(t1, pts[-1][0])
            if not per_rank:
                continue
            span = (t1 - t0) if (t0 is not None and t1 is not None) else 0.0
            out[qn] = {"kind": "counter", "delta": total,
                       "rate": (total / span) if span > 0 else None,
                       "t0": t0, "t1": t1, "per_rank": per_rank}
        else:
            per_rank = {}
            for proc, ser in ranks.items():
                v = last_value(ser["points"])
                if v is not None:
                    per_rank[proc] = v
            if per_rank:
                out[qn] = {"kind": kind, "per_rank": per_rank}
    return out
