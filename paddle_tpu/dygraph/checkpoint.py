"""save_dygraph / load_dygraph.

Parity: /root/reference/python/paddle/fluid/dygraph/checkpoint.py:33,96.
State dicts serialize to .npz (".pdparams"/".pdopt" naming kept).
Writes are atomic (tmp + fsync + rename, paddle_tpu/checkpoint.py):
a crash mid-save leaves the previous state dict, never a torn one.
"""
from __future__ import annotations

import io
import os

import numpy as np

from ..checkpoint import atomic_write_bytes
from .varbase import VarBase

__all__ = ["save_dygraph", "load_dygraph"]


def save_dygraph(state_dict, model_path):
    suffix = ".pdparams"
    for v in state_dict.values():
        if not getattr(v, "persistable", True):
            continue
    if any(not isinstance(v, VarBase) for v in state_dict.values()):
        suffix = ".pdopt"
    arrays = {}
    for k, v in state_dict.items():
        arrays[k] = v.numpy() if isinstance(v, VarBase) else np.asarray(v)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    atomic_write_bytes(model_path + suffix + ".npz", buf.getvalue())


def load_dygraph(model_path):
    params, opt = None, None
    p = model_path + ".pdparams.npz"
    if os.path.exists(p):
        data = np.load(p)
        params = {k: data[k] for k in data.files}
    o = model_path + ".pdopt.npz"
    if os.path.exists(o):
        data = np.load(o)
        opt = {k: data[k] for k in data.files}
    return params, opt
