"""ISSUE 12: static program verifier + cross-rank collective-
consistency checker + rewrite-invariant contracts.

Covers all four existing rewrite passes (insert_allreduce, bucket
pass incl. the profile-guided replan, sharded update, pipeline split)
plus the lazy-flush graph: a clean program verifies clean, every
seeded hazard from the tools/ir_mutate.py catalogue is caught, and a
dp=8 rank-divergent collective schedule is rejected with the diverging
op pair named.
"""
import importlib.util
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import analysis
from paddle_tpu.analysis import (CollectiveMismatchError,
                                 ContractViolation, IRVerificationError)
from paddle_tpu.parallel.collectives import bucket_allreduce_ops
from paddle_tpu.parallel.mesh_utils import make_mesh
from paddle_tpu.parallel.transpiler import insert_allreduce_ops

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True, scope="module")
def _preserve_global_rng():
    """Executor construction seeds its RNGState from the GLOBAL numpy
    stream; this module creates many executors and runs mid-alphabet,
    so without a restore every later test file would see a shifted
    stream (test_slim_compress's convergence threshold is sensitive to
    exactly that)."""
    state = np.random.get_state()
    yield
    np.random.set_state(state)

_spec = importlib.util.spec_from_file_location(
    "ir_mutate", os.path.join(ROOT, "tools", "ir_mutate.py"))
ir_mutate = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(ir_mutate)


def _build(optimizer="sgd"):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[16, 8], dtype="float32")
        lbl = fluid.data(name="lbl", shape=[16, 1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="relu")
        pred = fluid.layers.fc(h, size=10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, lbl))
        if optimizer == "momentum":
            fluid.optimizer.MomentumOptimizer(0.1, 0.9).minimize(loss)
        else:
            fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


class TestVerifier:
    def test_clean_program_verifies_clean(self):
        main, _, loss = _build()
        fs = analysis.verify_program(main, fetch_names=[loss.name],
                                     recheck_shapes=True)
        assert [f for f in fs if f.severity == "error"] == []

    def test_all_rewrite_passes_verify_clean(self):
        # insert_allreduce + bucket pass, then full verification with
        # shape recheck — the acceptance "every existing rewrite pass
        # passes verification clean"
        main, _, loss = _build()
        insert_allreduce_ops(main, 8)
        bucket_allreduce_ops(main, bucket_bytes=4 << 20)
        fs = analysis.verify_program(main, fetch_names=[loss.name],
                                     recheck_shapes=True)
        assert [f for f in fs if f.severity == "error"] == []
        assert analysis.schedule_record(main, nranks=8)["ok"]

    def test_error_is_structured(self):
        main, _, loss = _build()
        op = main.global_block().ops[0]
        op.inputs["X"] = ["__nope__"]
        with pytest.raises(IRVerificationError) as ei:
            analysis.verify_program(main, pass_name="unit")
        e = ei.value
        assert e.pass_name == "unit"
        assert e.findings and e.findings[0].invariant == "dangling-input"
        assert e.findings[0].op_type == op.type
        assert e.findings[0].block_idx == 0
        assert "__nope__" in str(e)

    @pytest.mark.parametrize(
        "kind", [m[0] for m in ir_mutate.MUTATIONS],
        ids=[m[0] for m in ir_mutate.MUTATIONS])
    def test_mutation_caught(self, kind):
        fn = dict((k, f) for k, _d, f in ir_mutate.MUTATIONS)[kind]
        flagged, detail = fn()
        assert flagged, detail


class TestCrossRank:
    def test_dp8_mismatched_schedule_names_diverging_pair(self):
        main, _, _ = _build()
        insert_allreduce_ops(main, 8)
        sigs, findings = analysis.extract_collective_schedule(main)
        assert not findings and len(sigs) >= 2
        import copy

        per_rank = [list(sigs) for _ in range(8)]
        per_rank[5] = list(per_rank[5])
        bad = per_rank[5][1] = copy.copy(per_rank[5][1])
        bad.dtype = "float16"
        with pytest.raises(CollectiveMismatchError) as ei:
            analysis.check_cross_rank(per_rank, where="dp8")
        e = ei.value
        assert e.kind == "would-corrupt"
        # the diverging op PAIR: (rank, position, sig) for both sides
        (r0, k0, a), (r5, k5, b) = e.pair
        assert (r0, r5) == (0, 5) and k0 == k5 == 1
        assert "rank 5" in str(e) and "rank 0" in str(e)
        assert a.op_type in str(e) and "float16" in str(e)

    def test_identical_schedules_pass(self):
        main, _, _ = _build()
        insert_allreduce_ops(main, 8)
        n = analysis.check_cross_rank([main] * 8)
        assert n >= 2


class TestContractsForFree:
    """A future pass author decorates with @checked_rewrite and gets
    post-rewrite verification without writing a contract."""

    def test_buggy_future_pass_caught(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_VERIFY_IR", "1")

        @analysis.checked_rewrite("future_pass")
        def buggy_pass(program):
            op = program.global_block().ops[0]
            op.inputs = {k: ["__gone__"] for k in op.inputs}

        main, _, _ = _build()
        with pytest.raises(IRVerificationError) as ei:
            buggy_pass(main)
        assert ei.value.pass_name == "future_pass"

    def test_disabled_flag_skips_checks(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_VERIFY_IR", "0")

        @analysis.checked_rewrite("future_pass")
        def buggy_pass(program):
            op = program.global_block().ops[0]
            op.inputs = {k: ["__gone__"] for k in op.inputs}

        main, _, _ = _build()
        buggy_pass(main)  # no verification, no raise

    def test_registered_contract_rides_decorator(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_VERIFY_IR", "1")
        calls = []

        class _C(analysis.RewriteContract):
            name = "future_pass2"

            def pre(self, program):
                calls.append("pre")
                return {"ops": len(program.global_block().ops)}

            def post(self, program, state):
                calls.append("post")
                if len(program.global_block().ops) != state["ops"]:
                    raise ContractViolation("op count changed")

        analysis.register_contract(_C())

        @analysis.checked_rewrite("future_pass2")
        def add_op_pass(program):
            import copy

            block = program.global_block()
            block.ops.append(copy.copy(block.ops[0]))

        main, _, _ = _build()
        with pytest.raises(ContractViolation):
            add_op_pass(main)
        assert calls == ["pre", "post"]


class TestEngineWiring:
    def test_engine_first_run_verifies(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_VERIFY_IR", "1")
        from paddle_tpu import observability as obs

        main, startup, loss = _build()
        scope = fluid.Scope()
        obs.enable()
        try:
            obs.reset()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                cp = fluid.CompiledProgram(main).with_data_parallel(
                    loss_name=loss.name, places=make_mesh([2], ["dp"]))
                feed = {"x": np.zeros((16, 8), "float32"),
                        "lbl": np.zeros((16, 1), "int64")}
                exe.run(cp, feed=feed, fetch_list=[loss])
                exe.run(cp, feed=feed, fetch_list=[loss])
            # the engine hook fires once (first run / compile miss),
            # not per step; the decorated (idempotent) passes re-check
            # on every invocation, so their counter only has a floor
            assert obs.counter_value("analysis.verify_runs",
                                     where="parallel.engine") == 1
            assert obs.counter_value("analysis.pass_checks",
                                     rewrite="insert_allreduce") >= 1
        finally:
            obs.disable()

    def test_engine_rejects_corrupt_program(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_VERIFY_IR", "1")
        main, startup, loss = _build()
        # corrupt AFTER build: the engine's first-run hook must refuse
        block = main.global_block()
        block.ops[2].inputs = {k: ["__gone__"]
                               for k in block.ops[2].inputs}
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            cp = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name, places=make_mesh([2], ["dp"]))
            feed = {"x": np.zeros((16, 8), "float32"),
                    "lbl": np.zeros((16, 1), "int64")}
            with pytest.raises(IRVerificationError):
                exe.run(cp, feed=feed, fetch_list=[loss])


class TestLoadWiring:
    def test_corrupt_saved_model_rejected_at_load(self, tmp_path,
                                                  monkeypatch):
        import json

        monkeypatch.setenv("PADDLE_TPU_VERIFY_IR", "1")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.unique_name.guard(), \
                fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[4, 8], dtype="float32")
            y = fluid.layers.fc(x, size=3, act=None)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        d = str(tmp_path / "model")
        fluid.io.save_inference_model(d, ["x"], [y], exe,
                                      main_program=main)
        # clean round trip verifies
        fluid.io.load_inference_model(d, exe)
        # corrupt the serialized program: dangle an input reference
        p = os.path.join(d, "__model__.json")
        with open(p) as f:
            doc = json.load(f)
        doc["blocks"][0]["ops"][0]["inputs"] = {
            k: ["__corrupt__"]
            for k in doc["blocks"][0]["ops"][0]["inputs"]}
        with open(p, "w") as f:
            json.dump(doc, f)
        # refresh the integrity manifest so the CHECKSUM gate passes
        # and the corruption reaches the semantic verifier — the case
        # this hook exists for is a well-formed file with bad contents
        from paddle_tpu.checkpoint import write_manifest

        write_manifest(d)
        with pytest.raises(IRVerificationError):
            fluid.io.load_inference_model(d, exe)


class TestPipelineSplitContract:
    def test_partition_must_tile_forward_range(self):
        main, _, _ = _build()
        ops = main.global_block().ops
        stages = [ops[:3], ops[2:6]]  # op 2 appears twice
        with pytest.raises(ContractViolation):
            analysis.check_pipeline_split(main, stages, 6)

    def test_empty_stage_rejected(self):
        main, _, _ = _build()
        ops = main.global_block().ops
        with pytest.raises(ContractViolation):
            analysis.check_pipeline_split(main, [ops[:6], []], 6)

    def test_good_partition_passes(self):
        main, _, _ = _build()
        ops = main.global_block().ops
        analysis.check_pipeline_split(main, [ops[:3], ops[3:6]], 6)
