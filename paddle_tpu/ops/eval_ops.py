"""Sequence-labeling / ranking evaluation ops.

Parity: /root/reference/paddle/fluid/operators/chunk_eval_op.cc
(IOB/IOE/IOBES/plain chunk F1 over LoD label sequences) and
positive_negative_pair_op.cc (per-query ranking pair counts). Both are
host ops — variable-length label walks and per-query hash grouping are
host-shaped work the reference also runs CPU-only.
"""
from __future__ import annotations

import numpy as np

from ..core.registry import In, Out, register_host_op

_SCHEMES = {
    # scheme -> (num_tag_types, tag_begin, tag_inside, tag_end, tag_single)
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _segments(labels, num_tag_types, other_type, tb, ti, te, ts):
    """Chunk segments [(begin, end, type)] of one sequence (reference
    ChunkEvalKernel::GetSegments with its ChunkBegin/ChunkEnd rules)."""

    def chunk_end(ptag, ptype, tag, typ):
        if ptype == other_type:
            return False
        if typ == other_type or typ != ptype:
            return True
        if ptag == tb or ptag == ti:
            return tag == tb or tag == ts
        return ptag in (te, ts)

    def chunk_begin(ptag, ptype, tag, typ):
        if ptype == other_type:
            return typ != other_type
        if typ == other_type:
            return False
        if typ != ptype or tag == tb or tag == ts:
            return True
        if tag in (ti, te):
            return ptag in (te, ts)
        return False

    segs = []
    in_chunk = False
    start = 0
    tag, typ = -1, other_type
    for i, lab in enumerate(labels):
        ptag, ptype = tag, typ
        tag = int(lab) % num_tag_types
        typ = int(lab) // num_tag_types
        if in_chunk and chunk_end(ptag, ptype, tag, typ):
            segs.append((start, i - 1, ptype))
            in_chunk = False
        if chunk_begin(ptag, ptype, tag, typ):
            start = i
            in_chunk = True
    if in_chunk:
        segs.append((start, len(labels) - 1, typ))
    return segs


@register_host_op(
    "chunk_eval",
    inputs=[In("Inference", no_grad=True), In("Label", no_grad=True),
            In("SeqLength", dispensable=True, no_grad=True)],
    outputs=[Out("Precision"), Out("Recall"), Out("F1-Score"),
             Out("NumInferChunks"), Out("NumLabelChunks"),
             Out("NumCorrectChunks")],
    attrs={"num_chunk_types": 1, "chunk_scheme": "IOB",
           "excluded_chunk_types": []})
def _chunk_eval(executor, op, scope):
    from ..core.tensor import LoDTensor

    scheme = op.attrs.get("chunk_scheme", "IOB")
    if scheme not in _SCHEMES:
        raise ValueError("unknown chunk scheme %r" % scheme)
    ntag, tb, ti, te, ts = _SCHEMES[scheme]
    ntype = int(op.attrs.get("num_chunk_types", 1))
    other = ntype
    excluded = set(int(x)
                   for x in op.attrs.get("excluded_chunk_types", []))

    seq_len = None
    if op.input("SeqLength"):
        seq_len = np.asarray(executor._read_var(
            scope, op.input("SeqLength")[0])).reshape(-1)

    def sequences(name):
        v = scope.find_var(name).raw()
        arr = np.asarray(v.array if isinstance(v, LoDTensor) else v)
        if isinstance(v, LoDTensor) and v.lod():
            flat = arr.reshape(-1)
            off = v.lod()[0]
            return [flat[off[i]:off[i + 1]]
                    for i in range(len(off) - 1)]
        if seq_len is not None:
            # dense [B, T] rows truncated at their true lengths
            # (reference chunk_eval_op.h:181 SeqLength path)
            rows = arr.reshape(len(seq_len), -1)
            return [rows[i, :int(seq_len[i])]
                    for i in range(len(seq_len))]
        return [arr.reshape(-1)]  # one dense sequence

    inf_seqs = sequences(op.input("Inference")[0])
    lab_seqs = sequences(op.input("Label")[0])
    n_inf = n_lab = n_correct = 0
    for inf, lab in zip(inf_seqs, lab_seqs):
        a = _segments(inf, ntag, other, tb, ti, te, ts)
        b = _segments(lab, ntag, other, tb, ti, te, ts)
        a = [s for s in a if s[2] not in excluded]
        b = [s for s in b if s[2] not in excluded]
        n_inf += len(a)
        n_lab += len(b)
        n_correct += len(set(a) & set(b))
    prec = n_correct / n_inf if n_inf else 0.0
    rec = n_correct / n_lab if n_lab else 0.0
    f1 = 2 * prec * rec / (prec + rec) if n_correct else 0.0
    w = executor._write_var
    w(scope, op.output("Precision")[0], np.asarray([prec], np.float32))
    w(scope, op.output("Recall")[0], np.asarray([rec], np.float32))
    w(scope, op.output("F1-Score")[0], np.asarray([f1], np.float32))
    w(scope, op.output("NumInferChunks")[0],
      np.asarray([n_inf], np.int64))
    w(scope, op.output("NumLabelChunks")[0],
      np.asarray([n_lab], np.int64))
    w(scope, op.output("NumCorrectChunks")[0],
      np.asarray([n_correct], np.int64))


@register_host_op(
    "positive_negative_pair",
    inputs=[In("Score", no_grad=True), In("Label", no_grad=True),
            In("QueryID", no_grad=True),
            In("AccumulatePositivePair", dispensable=True, no_grad=True),
            In("AccumulateNegativePair", dispensable=True, no_grad=True),
            In("AccumulateNeutralPair", dispensable=True, no_grad=True),
            In("Weight", dispensable=True, no_grad=True)],
    outputs=[Out("PositivePair"), Out("NegativePair"),
             Out("NeutralPair")],
    attrs={"column": 0})
def _positive_negative_pair(executor, op, scope):
    """Per-query ordered-pair counts (reference
    positive_negative_pair_op.h): for each query's doc pairs with
    unequal labels, a pair is positive when score order matches label
    order, negative when inverted; equal scores also count neutral."""

    def val(slot):
        names = op.input(slot)
        if not names:
            return None
        return np.asarray(executor._read_var(scope, names[0]))

    score = val("Score")
    label = val("Label").reshape(-1)
    query = val("QueryID").reshape(-1).astype(np.int64)
    weight = val("Weight")
    if weight is not None:
        weight = weight.reshape(-1)
    col = int(op.attrs.get("column", 0))
    if score.ndim == 1:
        score = score.reshape(-1, 1)
    if col < 0:
        col += score.shape[1]
    s = score[:, col]
    pos = neg = neu = 0.0
    accp, accn, accu = (val("AccumulatePositivePair"),
                        val("AccumulateNegativePair"),
                        val("AccumulateNeutralPair"))
    if accp is not None and accn is not None and accu is not None:
        pos = float(accp.reshape(-1)[0])
        neg = float(accn.reshape(-1)[0])
        neu = float(accu.reshape(-1)[0])
    by_query = {}
    for i in range(len(query)):
        by_query.setdefault(int(query[i]), []).append(i)
    for idxs in by_query.values():
        for a in range(len(idxs)):
            for b in range(a + 1, len(idxs)):
                i, j = idxs[a], idxs[b]
                if label[i] == label[j]:
                    continue
                w = ((weight[i] + weight[j]) * 0.5
                     if weight is not None else 1.0)
                if s[i] == s[j]:
                    neu += w
                if (s[i] - s[j]) * (label[i] - label[j]) > 0.0:
                    pos += w
                else:
                    neg += w
    wv = executor._write_var
    wv(scope, op.output("PositivePair")[0],
       np.asarray([pos], np.float32))
    wv(scope, op.output("NegativePair")[0],
       np.asarray([neg], np.float32))
    wv(scope, op.output("NeutralPair")[0],
       np.asarray([neu], np.float32))
