"""Dynamic-batching inference serving on top of ``PaddlePredictor``.

The inference stack stops at ``PaddlePredictor.run()`` — one
synchronous caller, one request per dispatch. That wastes the one thing
XLA is actually good at (one compiled dispatch over a large batch) and,
worse, every novel request batch size is a fresh multi-ms compile on
the serving path. This package closes the gap the reference project
covers with its C++ serving stack, TPU-native:

- ``batcher``  — ``DynamicBatcher``: queues requests as futures,
  assembles micro-batches under a max-size/timeout policy, and buckets
  batch sizes to a fixed ladder (padding + per-request unpadding) so
  the executor's jit cache converges to ``len(ladder)`` shapes;
- ``engine``   — ``ServingEngine``: N workers over one shared
  predictor, bounded queue with typed ``ServerOverloaded`` rejection,
  per-request deadlines dropped before dispatch, bucket warmup at
  start, graceful drain at stop;
- ``http``     — stdlib ``ThreadingHTTPServer``: ``POST /predict``,
  ``GET /healthz`` (machine-readable lifecycle), ``GET /metrics``
  (Prometheus text);
- ``fleet``    — ``FleetRouter``: the replica-fleet front end (shared
  admission control, cost-class load shedding with priority lanes,
  health-checked routing with bounded ejection, exactly-once hedged
  retries) over N replica processes — same ``predict``/``health``/
  ``stats`` surface as the engine, so the HTTP front serves a fleet
  unchanged;
- ``metrics``  — the always-on ``serving.*`` counter/histogram/gauge
  families in the PR-1 observability registry;
- ``decode``   — the continuous-batching autoregressive engine
  (``DecodeEngine``): paged KV cache, per-token-step scheduling,
  streaming ``/generate`` with token-level exactly-once failover —
  the second ``engine_kind`` the fleet can front.

Minimal use::

    from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor
    from paddle_tpu import serving

    predictor = create_paddle_predictor(AnalysisConfig(model_dir))
    engine = serving.ServingEngine(
        predictor, serving.ServingConfig(max_batch_size=16)).start()
    out = engine.predict({"img": x})          # in-process
    serving.serve(engine, port=8080)          # ...or over HTTP
"""
from __future__ import annotations

from . import batcher, decode, engine, fleet, http, metrics  # noqa: F401
from .batcher import (  # noqa: F401
    BatchPolicy, DynamicBatcher, default_ladder, pick_bucket)
from .decode import (  # noqa: F401
    DecodeConfig, DecodeEngine, DecodeStream, KVCacheConfig,
    KVCacheFull, PagedKVCache)
from .engine import (  # noqa: F401
    DeadlineExpired, EngineStopped, RequestTooLarge, ServerOverloaded,
    ServingConfig, ServingEngine, ServingError)
from .fleet import (  # noqa: F401
    FleetConfig, FleetRouter, ReplicaUnavailable, RequestShed)
from .http import ServingHTTPServer, serve, start_http_server  # noqa: F401

__all__ = [
    "BatchPolicy", "DynamicBatcher", "default_ladder", "pick_bucket",
    "ServingConfig", "ServingEngine", "ServingError", "ServerOverloaded",
    "DeadlineExpired", "EngineStopped", "RequestTooLarge",
    "DecodeConfig", "DecodeEngine", "DecodeStream",
    "KVCacheConfig", "KVCacheFull", "PagedKVCache",
    "FleetConfig", "FleetRouter", "RequestShed", "ReplicaUnavailable",
    "ServingHTTPServer", "serve", "start_http_server",
]
