"""Imperative (dygraph) mode.

Parity: /root/reference/python/paddle/fluid/dygraph/ + the C++
imperative/ runtime (SURVEY.md §2.2): guard, to_variable, Layer, nn
layers, tape autograd (Tracer/BasicEngine), save/load, DataParallel
(parallel.py), TracedLayer (jit.py).
"""
from .base import (  # noqa: F401
    disable_dygraph,
    enable_dygraph,
    enabled,
    guard,
    no_grad,
    to_variable,
)
from .layers import Layer  # noqa: F401
from .varbase import ParamBase, VarBase  # noqa: F401
from .tracer import Tracer  # noqa: F401
from . import nn  # noqa: F401
from .nn import *  # noqa: F401,F403
from .checkpoint import load_dygraph, save_dygraph  # noqa: F401
from . import math_patch  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    CosineDecay,
    ExponentialDecay,
    InverseTimeDecay,
    NaturalExpDecay,
    NoamDecay,
    PiecewiseDecay,
    PolynomialDecay,
)
from .parallel import DataParallel, ParallelEnv, prepare_context  # noqa: F401
from .tracer import grad  # noqa: F401
from .jit import TracedLayer  # noqa: F401
from . import dygraph_to_static  # noqa: F401
from .dygraph_to_static import ProgramTranslator, declarative, to_static  # noqa: F401
