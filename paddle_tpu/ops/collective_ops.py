"""Collective communication ops (`c_*`).

Parity: /root/reference/paddle/fluid/operators/collective/ (c_allreduce_
{sum,max,min,prod}, c_broadcast, c_allgather, c_reducescatter,
c_gen_nccl_id, c_comm_init, c_sync_calc_stream, c_sync_comm_stream) —
lowered TPU-natively:

- Inside a mesh-mapped trace (pjit/shard_map data parallelism, see
  paddle_tpu/parallel/), ``ring_id`` resolves to a *named mesh axis* and
  the op emits the XLA collective (lax.psum / all_gather / psum_scatter)
  that rides ICI — replacing the reference's ncclAllReduce kernels keyed
  by NCCLCommContext ring_id.
- Outside any mapped context (single process, world=1) they are identity,
  matching reference behavior with nranks=1.
- Bootstrap ops (gen_nccl_id/comm_init) are no-op hosts: rendezvous is
  jax.distributed's coordination service over DCN, set up at launch
  (dygraph/parallel.py prepare_context), not graph ops. Stream-sync ops are no-ops: XLA
  program order subsumes them.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..core.registry import In, Out, register_host_op, register_op

# ring_id -> mesh axis name, set while tracing under shard_map
_ACTIVE_RING_AXES: Dict[int, str] = {}


class ring_axis_guard:
    """Context manager used by the parallel compiler: maps ring ids to the
    mesh axis names live in the current mapped trace."""

    def __init__(self, mapping: Dict[int, str]):
        self.mapping = dict(mapping)

    def __enter__(self):
        self._saved = dict(_ACTIVE_RING_AXES)
        _ACTIVE_RING_AXES.update(self.mapping)
        return self

    def __exit__(self, *exc):
        _ACTIVE_RING_AXES.clear()
        _ACTIVE_RING_AXES.update(self._saved)
        return False


def axis_for_ring(ring_id: int) -> Optional[str]:
    return _ACTIVE_RING_AXES.get(ring_id, _ACTIVE_RING_AXES.get(-1))


# mesh axis names live in the current mapped trace — lets hybrid-parallel
# ops (sharded lookup / ring attention / MoE) pick their parallel path
# inside the mesh engine and their exact dense fallback everywhere else
_ACTIVE_MESH_AXES: set = set()


class mesh_axes_guard:
    """Context manager set by the mesh engine while tracing under
    shard_map: declares which named axes are live."""

    def __init__(self, axes):
        self.axes = set(axes or ())

    def __enter__(self):
        self._saved = set(_ACTIVE_MESH_AXES)
        _ACTIVE_MESH_AXES.update(self.axes)
        return self

    def __exit__(self, *exc):
        _ACTIVE_MESH_AXES.clear()
        _ACTIVE_MESH_AXES.update(self._saved)
        return False


def mesh_axis_active(name: Optional[str]) -> bool:
    return bool(name) and name in _ACTIVE_MESH_AXES


def _allreduce(name, reducer):
    @register_op(
        name,
        inputs=[In("X")],
        outputs=[Out("Out")],
        attrs={"ring_id": 0, "use_calc_stream": False, "use_model_parallel": False},
        grad=None,
    )
    def _op(ins, attrs, _red=reducer):
        axis = axis_for_ring(attrs.get("ring_id", 0))
        x = ins["X"]
        return {"Out": x if axis is None else _red(x, axis)}

    return _op


_allreduce("c_allreduce_sum", lambda x, ax: jax.lax.psum(x, ax))
_allreduce("c_allreduce_max", lambda x, ax: jax.lax.pmax(x, ax))
_allreduce("c_allreduce_min", lambda x, ax: jax.lax.pmin(x, ax))
_allreduce("c_allreduce_prod", lambda x, ax: jnp.exp(jax.lax.psum(jnp.log(x), ax)))


@register_op(
    "c_broadcast",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"ring_id": 0, "root": 0, "use_calc_stream": False},
    grad=None,
)
def _c_broadcast(ins, attrs):
    axis = axis_for_ring(attrs.get("ring_id", 0))
    x = ins["X"]
    if axis is None:
        return {"Out": x}
    # select root's value on every member of the axis
    root = attrs.get("root", 0)
    full = jax.lax.all_gather(x, axis)
    return {"Out": full[root]}


@register_op(
    "c_allgather",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"ring_id": 0, "nranks": 1, "use_calc_stream": False},
    grad=None,
)
def _c_allgather(ins, attrs):
    axis = axis_for_ring(attrs.get("ring_id", 0))
    x = ins["X"]
    if axis is None:
        return {"Out": x}
    g = jax.lax.all_gather(x, axis)  # [nranks, ...]
    return {"Out": g.reshape((-1,) + x.shape[1:])}


@register_op(
    "c_reducescatter",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"ring_id": 0, "nranks": 1, "use_calc_stream": False},
    grad=None,
)
def _c_reducescatter(ins, attrs):
    axis = axis_for_ring(attrs.get("ring_id", 0))
    x = ins["X"]
    if axis is None:
        return {"Out": x}
    return {"Out": jax.lax.psum_scatter(x, axis, tiled=True)}


@register_op(
    "c_concat",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"ring_id": 0, "nranks": 1, "rank": 0},
    grad=None,
)
def _c_concat(ins, attrs):
    axis = axis_for_ring(attrs.get("ring_id", 0))
    x = ins["X"]
    if axis is None:
        return {"Out": x}
    g = jax.lax.all_gather(x, axis)
    return {"Out": jnp.concatenate([g[i] for i in range(g.shape[0])], axis=-1)}


@register_op(
    "alltoall",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"ring_id": 0},
    grad=None,
)
def _alltoall(ins, attrs):
    axis = axis_for_ring(attrs.get("ring_id", 0))
    x = ins["X"]
    if axis is None:
        return {"Out": x}
    n = jax.lax.axis_size(axis)
    xs = x.reshape((n, x.shape[0] // n) + x.shape[1:])
    out = jax.lax.all_to_all(xs, axis, split_axis=0, concat_axis=0, tiled=False)
    return {"Out": out.reshape(x.shape)}


# -- bootstrap / sync: no-ops under the XLA model ---------------------------


@register_host_op("c_gen_nccl_id", inputs=[], outputs=[Out("Out", dispensable=True)],
                  attrs={"rank": 0, "endpoint": "", "other_endpoints": [],
                         "ring_id": 0})
def _c_gen_nccl_id(executor, op, scope):
    # Rendezvous is handled by jax.distributed (coordination service over
    # DCN) at process launch; nothing to do per-ring.
    pass


@register_host_op("c_comm_init", inputs=[In("X", dispensable=True)], outputs=[],
                  attrs={"nranks": 1, "rank": 0, "device_id": 0, "ring_id": 0})
def _c_comm_init(executor, op, scope):
    pass


@register_host_op("c_sync_calc_stream", inputs=[In("X")], outputs=[Out("Out")],
                  attrs={})
def _c_sync_calc_stream(executor, op, scope):
    # XLA program order subsumes stream sync; keep data flowing through.
    executor._write_var(scope, op.output("Out")[0],
                        executor._read_var(scope, op.input("X")[0]))


@register_host_op("c_sync_comm_stream", inputs=[In("X")], outputs=[Out("Out")],
                  attrs={"ring_id": 0})
def _c_sync_comm_stream(executor, op, scope):
    executor._write_var(scope, op.output("Out")[0],
                        executor._read_var(scope, op.input("X")[0]))


@register_host_op("barrier", inputs=[In("X", dispensable=True)],
                  outputs=[Out("Out", dispensable=True)], attrs={"ring_id": 0})
def _barrier(executor, op, scope):
    pass


@register_op(
    "allreduce",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"reduce_type": 0, "sync_mode": False},
    grad=None,
)
def _allreduce_legacy(ins, attrs):
    """Legacy dygraph-DP allreduce (reference
    distributed_ops/allreduce_op.cc; reduce_type 0..3 =
    sum/prod/max/min over the default ring). Same lowering as
    c_allreduce_* — a psum-family collective over the ring-0 axis."""
    axis = axis_for_ring(0)
    x = ins["X"]
    if axis is None:
        return {"Out": x}
    rt = int(attrs.get("reduce_type", 0))
    fns = {0: jax.lax.psum, 1: _pprod, 2: jax.lax.pmax, 3: jax.lax.pmin}
    if rt not in fns:
        raise ValueError("allreduce: bad reduce_type %d" % rt)
    return {"Out": fns[rt](x, axis)}


def _pprod(x, ax):
    return jnp.exp(jax.lax.psum(jnp.log(jnp.abs(x) + 1e-38), ax)) * \
        jnp.where(jax.lax.psum((x < 0).astype(jnp.int32), ax) % 2 == 1,
                  -1.0, 1.0)


@register_op(
    "broadcast",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"sync_mode": False, "root": 0},
    grad=None,
)
def _broadcast_legacy(ins, attrs):
    """Legacy dygraph-DP broadcast (reference
    distributed_ops/broadcast_op.cc) — same lowering as c_broadcast on
    ring 0."""
    return _c_broadcast(ins, {**attrs, "ring_id": 0})
