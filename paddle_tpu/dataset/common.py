"""Dataset plumbing (reference python/paddle/dataset/common.py).

DATA_HOME cache layout and md5 checks match the reference;
``download`` only serves from the local cache — this environment has no
network egress, so a missing file raises with instructions instead of
fetching. Every dataset module therefore falls back to a deterministic
synthetic reader when its files are absent (the reference's sample
contracts are preserved so book-style tests behave the same).
"""
from __future__ import annotations

import hashlib
import os
import pickle

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_HOME",
    os.path.expanduser(os.path.join("~", ".cache", "paddle_tpu",
                                    "dataset")))


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)
    return path


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum=None, save_name=None):
    """Serve from the local cache; no egress in this environment."""
    dirname = must_mkdirs(os.path.join(DATA_HOME, module_name))
    filename = os.path.join(
        dirname, save_name if save_name else url.split("/")[-1])
    if os.path.exists(filename):
        if md5sum is None or md5file(filename) == md5sum:
            return filename
    raise RuntimeError(
        "dataset file %s is not cached and this environment has no "
        "network egress; place the file at %s manually (source url: %s)"
        % (os.path.basename(filename), filename, url))


def cached_path(module_name, filename):
    """Path inside DATA_HOME if it exists, else None."""
    p = os.path.join(DATA_HOME, module_name, filename)
    return p if os.path.exists(p) else None


def cycled(reader):
    """Wrap a reader creator to repeat forever (the reference's
    cycle=True contract)."""
    def cyc():
        while True:
            yield from reader()

    return cyc


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """(reference common.py cluster_files_reader) — round-robin split of
    matched files across trainers."""
    import glob

    def reader():
        flist = sorted(glob.glob(files_pattern))
        my = flist[trainer_id::trainer_count]
        for fn in my:
            with open(fn, "rb") as f:
                d = loader(f)
                for item in d:
                    yield item

    return reader
