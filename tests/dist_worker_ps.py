"""Worker for the two-process parameter-server test.

Role from PADDLE_TRAINING_ROLE (the reference's env contract):
PSERVER blocks in listen_and_serv over the real socket RPC
(PADDLE_PSERVER_RPC=1); TRAINER runs the transpiled trainer program,
training through send/recv against the live server, then asks the
server for the final param and writes a JSON result.
"""
import json
import os
import sys

import numpy as np

import paddle_tpu as fluid

STEPS = 5
BS = 16


def _net():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[BS, 8], dtype="float32")
        y = fluid.data(name="y", shape=[BS, 1], dtype="float32")
        pred = fluid.layers.fc(
            x, 1,
            param_attr=fluid.ParamAttr(
                name="w",
                initializer=fluid.initializer.ConstantInitializer(0.3)),
            bias_attr=fluid.ParamAttr(
                name="b",
                initializer=fluid.initializer.ConstantInitializer(0.0)))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


def main():
    role = os.environ["PADDLE_TRAINING_ROLE"]
    endpoint = os.environ["PSERVER_ENDPOINT"]
    out_path = sys.argv[1]

    main_prog, startup, loss = _net()
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main_prog, startup_program=startup,
                pservers=endpoint, trainers=1, sync_mode=True)

    if role == "PSERVER":
        os.environ["PADDLE_PSERVER_RPC"] = "1"
        ps_prog = t.get_pserver_program(endpoint)
        exe = fluid.Executor(fluid.CPUPlace())
        exe._core.rng.seed = 77
        exe._core.rng.step = 0
        exe.run(t.get_startup_program(endpoint, ps_prog))
        exe.run(ps_prog)  # blocks serving until shutdown
        return

    # trainer
    exe = fluid.Executor(fluid.CPUPlace())
    exe._core.rng.seed = 77
    exe._core.rng.step = 0
    exe.run(startup)
    rng = np.random.RandomState(5)
    W = rng.randn(8, 1).astype("float32")
    losses = []
    for _ in range(STEPS):
        xb = rng.randn(BS, 8).astype("float32")
        (l,) = exe.run(main_prog, feed={"x": xb, "y": xb @ W},
                       fetch_list=[loss])
        losses.append(float(np.asarray(l).ravel()[0]))

    from paddle_tpu.distributed.ps_rpc import PSClient

    client = PSClient.for_endpoint(endpoint)
    w_final = client.get_param("w")
    hb = client.heartbeat()
    client.shutdown_server()
    with open(out_path, "w") as f:
        f.write(json.dumps({"losses": losses,
                            "w_sum": float(np.abs(w_final).sum()),
                            "heartbeat_trainers": sorted(hb)}))


if __name__ == "__main__":
    main()
