"""Multi-process STATIC-graph data parallelism (the collective-fleet
arm, round-3 follow-up to the dygraph test): 2 OS processes run
CompiledProgram.with_data_parallel over a global 2-device mesh; per-step
losses must match the single-process full-batch run and both ranks'
params stay identical."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# jax CPU builds without multiprocess collective support fail the
# 2-process mesh with this marker — an environment limit, not a
# regression (the single-process oracle still runs)
_NO_MP_COLLECTIVES = "aren't implemented on the CPU backend"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dist_worker_fleet.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("XLA_FLAGS", None)
    for k in list(env):
        if k.startswith(("PADDLE_", "JAX_COORDINATOR", "JAX_NUM_PROC",
                         "JAX_PROCESS")):
            env.pop(k, None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _single_process_oracle(tmp_path):
    """Same model, full batch, one process (parity target)."""
    out = str(tmp_path / "oracle")
    proc = subprocess.run(
        [sys.executable, WORKER, out],
        env={**_env(), "PADDLE_TRAINERS_NUM": "1",
             "PADDLE_TRAINER_ID": "0", "ORACLE_WORLD": "2"},
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return json.loads(open(out + ".rank0").read())


def test_two_process_fleet_converges_under_faults(tmp_path):
    """Second fault-injection CI path (ROADMAP): the collective-fleet
    workers pull every step's batch over the ps_rpc transport — every
    frame through distributed/fault.py — with 2% of sends dropped.
    Client retry + seq-matched responses must absorb the losses: the
    job completes and the per-step losses still match the clean
    single-process oracle exactly (a dropped-then-retried pull feeds
    the same bytes)."""
    from paddle_tpu.distributed.ps_rpc import PSServer

    oracle = _single_process_oracle(tmp_path)

    class _Scope(dict):
        def local_var_names(self):
            return list(self)

    class _Exec:
        def _read_var(self, scope, name):
            return scope.get(name)

        def _write_var(self, scope, name, val):
            scope[name] = np.asarray(val)

        def run_block(self, block, scope):
            block(scope)

    # the data server precomputes the same rng(7) batch sequence the
    # workers would have generated locally (world=2 global batches)
    scope = _Scope()
    rng = np.random.RandomState(7)
    for step in range(3):  # dist_worker_fleet.STEPS
        scope["x_s%d" % step] = rng.randn(16, 12).astype("float32")
        scope["y_s%d" % step] = rng.randint(0, 10, (16, 1)).astype(
            "int64")
    endpoint = "127.0.0.1:%d" % _free_port()
    server = PSServer(endpoint, _Exec(), scope, {}, fanin=2,
                      sync_mode=False)
    server.start_background()

    out = str(tmp_path / "fleet_faults")
    env = _env()
    env.update({
        "FLEET_DATA_ENDPOINT": endpoint,
        "PADDLE_TPU_FAULTS": "send.drop:0.02",
        "PADDLE_TPU_FAULT_SEED": "7",
        "PADDLE_PS_RPC_DEADLINE": "2.0",
        "PADDLE_PS_RPC_RETRIES": "12",
        "PADDLE_PS_RPC_BACKOFF_MS": "20",
    })
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node=2", "--max_restarts=0",
             "--started_port=%d" % _free_port(),
             WORKER, out],
            env=env, capture_output=True, text=True, timeout=300)
        if proc.returncode != 0 and _NO_MP_COLLECTIVES in proc.stderr:
            pytest.skip("2-process CPU collectives unavailable: %s"
                        % _NO_MP_COLLECTIVES)
        assert proc.returncode == 0, (proc.stdout[-1000:],
                                      proc.stderr[-3000:])
        ranks = [json.loads(open("%s.rank%d" % (out, r)).read())
                 for r in (0, 1)]
        np.testing.assert_allclose(ranks[0]["losses"],
                                   ranks[1]["losses"], rtol=1e-6)
        np.testing.assert_allclose(ranks[0]["losses"],
                                   oracle["losses"], rtol=1e-5,
                                   atol=1e-6)
        assert abs(ranks[0]["checksum"] - ranks[1]["checksum"]) < 1e-6
    finally:
        server.stop()


def test_two_process_static_dp(tmp_path):
    oracle = _single_process_oracle(tmp_path)
    assert oracle["nranks"] == 1

    out = str(tmp_path / "fleet")
    port = _free_port()
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node=2", "--started_port=%d" % port,
         WORKER, out],
        env=_env(), capture_output=True, text=True, timeout=300)
    if proc.returncode != 0 and _NO_MP_COLLECTIVES in proc.stderr:
        pytest.skip("2-process CPU collectives unavailable: %s"
                    % _NO_MP_COLLECTIVES)
    assert proc.returncode == 0, (proc.stdout[-1000:],
                                  proc.stderr[-3000:])
    ranks = [json.loads(open("%s.rank%d" % (out, r)).read())
             for r in (0, 1)]

    # both ranks observed the same (global) per-step losses, equal to
    # the single-process full-batch run
    np.testing.assert_allclose(ranks[0]["losses"], ranks[1]["losses"],
                               rtol=1e-6)
    np.testing.assert_allclose(ranks[0]["losses"], oracle["losses"],
                               rtol=1e-5, atol=1e-6)
    # replicated updates kept params bitwise-aligned
    assert abs(ranks[0]["checksum"] - ranks[1]["checksum"]) < 1e-6
    assert abs(ranks[0]["checksum"] - oracle["checksum"]) < 1e-4
