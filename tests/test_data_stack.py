"""Data stack tests: native C++ feed, Dataset factory, DataLoader
(thread + multiprocess + device prefetch), dataset readers,
train_from_dataset.

Contracts: reference data_feed.cc MultiSlotDataFeed record format,
dataset.py InMemoryDataset/QueueDataset, reader.py DataLoader."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as fluid


def _write_multislot(path, n_lines, seed=0, dense=4):
    """Lines: dense slot (count=dense floats) + label slot (1 int)."""
    rng = np.random.RandomState(seed)
    rows = []
    with open(path, "w") as f:
        for _ in range(n_lines):
            vals = rng.rand(dense).round(4)
            label = rng.randint(0, 10)
            rows.append((vals, label))
            f.write("%d %s 1 %d\n" % (
                dense, " ".join("%g" % v for v in vals), label))
    return rows


class TestNativeFeed:
    def test_parses_batches(self):
        from paddle_tpu.core.native_feed import NativeMultiSlotFeed, load

        if load() is None:
            pytest.skip("no native toolchain")
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "part-0")
            rows = _write_multislot(p, 10)
            feed = NativeMultiSlotFeed([p], ["float", "int64"],
                                       batch_size=5, num_threads=1)
            batches = list(feed)
            feed.close()
        assert len(batches) == 2
        total_labels = []
        for slots in batches:
            fvals, foffs = slots[0]
            ivals, ioffs = slots[1]
            assert len(foffs) == 6 and len(ioffs) == 6
            assert len(fvals) == 20  # 5 rows x 4 dense vals
            total_labels.extend(ivals.tolist())
        assert sorted(total_labels) == sorted(r[1] for r in rows)

    def test_matches_python_fallback(self):
        from paddle_tpu.core.native_feed import NativeMultiSlotFeed, load
        from paddle_tpu.dataset_module import _python_multislot_feed

        if load() is None:
            pytest.skip("no native toolchain")
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "part-0")
            _write_multislot(p, 8, seed=3)
            nat = list(NativeMultiSlotFeed([p], ["float", "int64"], 4,
                                           num_threads=1))
            py = list(_python_multislot_feed([p], ["float", "int64"], 4))
        assert len(nat) == len(py)
        for nb, pb in zip(nat, py):
            for (nv, no), (pv, po) in zip(nb, pb):
                np.testing.assert_allclose(nv, pv, rtol=1e-6)
                np.testing.assert_array_equal(no, po)


class TestDatasetFactory:
    def _dataset(self, cls, d, batch=4):
        p = os.path.join(d, "part-0")
        _write_multislot(p, 12)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[batch, 4], dtype="float32")
            y = fluid.data(name="y", shape=[batch, 1], dtype="int64")
        ds = fluid.DatasetFactory().create_dataset(cls)
        ds.set_batch_size(batch)
        ds.set_use_var([x, y])
        ds.set_filelist([p])
        return ds

    def test_queue_dataset_batches(self):
        with tempfile.TemporaryDirectory() as d:
            ds = self._dataset("QueueDataset", d)
            batches = list(ds._iter_batches())
        assert len(batches) == 3
        for b in batches:
            assert b["x"].shape == (4, 4)
            assert b["y"].shape == (4, 1)

    def test_inmemory_shuffle_keeps_records(self):
        with tempfile.TemporaryDirectory() as d:
            ds = self._dataset("InMemoryDataset", d)
            ds.load_into_memory()
            before = sorted(
                float(np.asarray(r["x"]).ravel()[0]) for r in ds._records)
            ds.local_shuffle()
            after = sorted(
                float(np.asarray(r["x"]).ravel()[0]) for r in ds._records)
            assert before == after
            batches = list(ds._iter_batches())
        assert len(batches) == 3

    def test_train_from_dataset(self):
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "part-0")
            _write_multislot(p, 64, seed=1)
            B = 8
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.data(name="x", shape=[B, 4], dtype="float32")
                y = fluid.data(name="y", shape=[B, 1], dtype="int64")
                pred = fluid.layers.fc(x, 10, act="softmax")
                loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
                fluid.optimizer.SGD(0.1).minimize(loss)
            ds = fluid.DatasetFactory().create_dataset("QueueDataset")
            ds.set_batch_size(B)
            ds.set_use_var([x, y])
            ds.set_filelist([p])
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                w = main.global_block().all_parameters[0].name
                before = np.asarray(scope.find_var(w).raw().array).copy()
                exe.train_from_dataset(main, ds, fetch_list=[loss])
                after = np.asarray(scope.find_var(w).raw().array)
            assert not np.allclose(before, after)  # trained


class TestDataLoader:
    def _check_loader(self, use_multiprocess):
        B = 4
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[B, 3], dtype="float32")
        loader = fluid.DataLoader.from_generator(
            feed_list=[x], capacity=4, use_multiprocess=use_multiprocess)

        def gen():
            rng = np.random.RandomState(0)
            for i in range(6):
                yield [rng.rand(B, 3).astype("float32")]

        loader.set_batch_generator(gen)
        seen = list(loader)
        assert len(seen) == 6
        ref = np.random.RandomState(0)
        for batch in seen:
            np.testing.assert_allclose(np.asarray(batch["x"]),
                                       ref.rand(B, 3).astype("float32"),
                                       rtol=1e-6)

    def test_thread_loader_with_prefetch(self):
        self._check_loader(use_multiprocess=False)

    def test_multiprocess_loader(self):
        self._check_loader(use_multiprocess=True)

    def test_loader_feeds_executor(self):
        B = 8
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[B, 4], dtype="float32")
            y = fluid.data(name="y", shape=[B, 1], dtype="float32")
            loss = fluid.layers.mean(fluid.layers.square_error_cost(
                fluid.layers.fc(x, 1), y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        loader = fluid.DataLoader.from_generator(feed_list=[x, y],
                                                 capacity=4)
        rng = np.random.RandomState(0)
        W = rng.randn(4, 1).astype("float32")

        def gen():
            r = np.random.RandomState(1)
            for i in range(20):
                xb = r.randn(B, 4).astype("float32")
                yield [xb, xb @ W]

        loader.set_batch_generator(gen)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            losses = []
            for feed in loader:
                (l,) = exe.run(main, feed=feed, fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
        assert losses[-1] < 0.5 * losses[0]


class TestDatasetReaders:
    def test_mnist_contract(self):
        from paddle_tpu.dataset import mnist

        it = mnist.train()()
        img, label = next(it)
        assert img.shape == (784,) and img.dtype == np.float32
        assert -1.0 <= float(img.min()) and float(img.max()) <= 1.0
        assert 0 <= label < 10

    def test_uci_housing_contract(self):
        from paddle_tpu.dataset import uci_housing

        x, y = next(uci_housing.train()())
        assert x.shape == (13,) and y.shape == (1,)


class TestInferencePredictor:
    def test_train_save_load_serve_roundtrip(self):
        from paddle_tpu import models
        from paddle_tpu.inference import (AnalysisConfig, PaddleTensor,
                                          create_paddle_predictor)

        B = 8
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.data(name="img", shape=[B, 1, 28, 28],
                             dtype="float32")
            label = fluid.data(name="label", shape=[B, 1], dtype="int64")
            pred = models.lenet(img)
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, label))
            fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
        rng = np.random.RandomState(0)
        scope = fluid.Scope()
        with tempfile.TemporaryDirectory() as d:
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                for i in range(3):
                    x = rng.rand(B, 1, 28, 28).astype("float32")
                    y = rng.randint(0, 10, (B, 1)).astype("int64")
                    exe.run(main, feed={"img": x, "label": y},
                            fetch_list=[loss])
                x = rng.rand(B, 1, 28, 28).astype("float32")
                (ref,) = exe.run(main.clone(for_test=True),
                                 feed={"img": x,
                                       "label": np.zeros((B, 1), "int64")},
                                 fetch_list=[pred])
                fluid.io.save_inference_model(d, ["img"], [pred], exe,
                                              main_program=main)
            config = AnalysisConfig(d)
            config.disable_gpu()
            predictor = create_paddle_predictor(config)
            assert predictor.get_input_names() == ["img"]
            (out,) = predictor.run([PaddleTensor(x, name="img")])
            np.testing.assert_allclose(out.as_ndarray(), np.asarray(ref),
                                       rtol=1e-4, atol=1e-5)
            # repeat call exercises the compiled-once path
            (out2,) = predictor.run({"img": x})
            np.testing.assert_allclose(out2.as_ndarray(),
                                       out.as_ndarray(), rtol=1e-6)

            # zero-copy surface — the EXACT call sequence the R
            # reticulate client performs (r/example/uci_housing.r);
            # this test pins that surface since CI has no R runtime
            name = predictor.get_input_names()[0]
            t_in = predictor.get_input_tensor(name)
            t_in.reshape([B, 1, 28, 28])
            t_in.copy_from_cpu(x.reshape(-1))
            predictor.zero_copy_run()
            t_out = predictor.get_output_tensor(
                predictor.get_output_names()[0])
            np.testing.assert_allclose(t_out.copy_to_cpu(),
                                       out.as_ndarray(), rtol=1e-6)
            assert t_out.shape() == list(out.as_ndarray().shape)


class TestInstallCheck:
    def test_run_check_multi_device(self, capsys):
        import jax

        import paddle_tpu

        assert paddle_tpu.install_check.run_check() is True
        out = capsys.readouterr().out
        if len(jax.devices()) > 1:
            assert "works well on %d devices" % len(jax.devices()) in out
        else:
            assert "skipped" in out


class TestFlagsAndErrors:
    def test_nan_checker_catches_inf(self):
        import paddle_tpu
        from paddle_tpu.core.enforce import EnforceNotMet

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[4], dtype="float32")
            y = fluid.layers.log(x)  # log(0) = -inf
        paddle_tpu.set_flags({"FLAGS_check_nan_inf": True})
        try:
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                # force the interpreter path so the per-op checker runs
                with pytest.raises(EnforceNotMet, match="Inf/Nan"):
                    exe._core.run_program(
                        main, scope, {"x": np.zeros(4, "float32")}, [y],
                        True)
        finally:
            paddle_tpu.set_flags({"FLAGS_check_nan_inf": False})

    def test_unknown_op_error_has_context(self):
        from paddle_tpu.core.enforce import NotFoundError
        from paddle_tpu.core.registry import OpInfoMap

        with pytest.raises(NotFoundError, match="conv2d"):
            OpInfoMap.instance().get("conv2dd")

    def test_get_set_flags_roundtrip(self):
        import paddle_tpu

        assert paddle_tpu.get_flags("FLAGS_allocator_strategy") == {
            "FLAGS_allocator_strategy": "auto_growth"}
        with pytest.raises(ValueError):
            paddle_tpu.get_flags("FLAGS_no_such_flag")


class TestMalformedRecords:
    def _write_bad(self, p):
        with open(p, "w") as f:
            f.write("4 0.1 0.2 0.3 0.4 1 7\n")   # good
            f.write("4 0.1 0.2 1 3\n")            # short dense slot
            f.write("x y z\n")                    # garbage
            f.write("4 0.5 0.6 0.7 0.8 1 2\n")   # good

    def test_native_skips_malformed_without_corruption(self):
        from paddle_tpu.core.native_feed import NativeMultiSlotFeed, load

        if load() is None:
            pytest.skip("no native toolchain")
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "part-0")
            self._write_bad(p)
            batches = list(NativeMultiSlotFeed([p], ["float", "int64"], 2,
                                               num_threads=1))
        assert len(batches) == 1
        fvals, foffs = batches[0][0]
        ivals, _ = batches[0][1]
        np.testing.assert_allclose(
            fvals, [0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8], rtol=1e-6)
        assert ivals.tolist() == [7, 2]
        assert foffs.tolist() == [0, 4, 8]  # no stray values

    def test_python_fallback_skips_malformed(self):
        from paddle_tpu.dataset_module import _python_multislot_feed

        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "part-0")
            self._write_bad(p)
            batches = list(_python_multislot_feed([p], ["float", "int64"],
                                                  2))
        assert len(batches) == 1
        assert batches[0][1][0].tolist() == [7, 2]


class TestLoaderErrorPropagation:
    def test_thread_producer_error_raises(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[2, 2], dtype="float32")
        loader = fluid.DataLoader.from_generator(feed_list=[x], capacity=2)

        def gen():
            yield [np.zeros((2, 2), "float32")]
            raise RuntimeError("reader exploded")

        loader.set_batch_generator(gen)
        with pytest.raises(RuntimeError, match="reader exploded"):
            list(loader)

    def test_mp_worker_hard_crash_raises(self):
        """A worker that dies without reporting (os._exit — simulating
        OOM-kill / native crash) must raise a clear error, not hang
        (reference imperative/data_loader.cc SIGCHLD handling)."""
        import os as _os
        import time as _time

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[2, 2], dtype="float32")
        loader = fluid.DataLoader.from_generator(
            feed_list=[x], capacity=2, use_multiprocess=True)

        def gen():
            yield [np.zeros((2, 2), "float32")]
            _os._exit(3)  # hard death: no exception ships

        loader.set_batch_generator(gen)
        t0 = _time.time()
        with pytest.raises(RuntimeError,
                           match="died|unexpectedly|crashed"):
            list(loader)
        assert _time.time() - t0 < 30

    def test_mp_worker_normal_end_no_alarm(self):
        """Clean worker exits must NOT trip the SIGCHLD alarm."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[2, 2], dtype="float32")
        loader = fluid.DataLoader.from_generator(
            feed_list=[x], capacity=2, use_multiprocess=True)

        def gen():
            for _ in range(3):
                yield [np.ones((2, 2), "float32")]

        loader.set_batch_generator(gen)
        assert len(list(loader)) == 3
        # and a second epoch still works (handler stays healthy)
        assert len(list(loader)) == 3


class TestPredictorIrPasses:
    def test_conv_bn_fold_in_predictor_prepare(self):
        """The predictor's prepare runs the ir fusion passes (reference
        AnalysisPredictor pass pipeline, paddle_pass_builder.cc):
        conv+BN folds into the conv weights, outputs unchanged."""
        import tempfile

        from paddle_tpu.inference import (AnalysisConfig, PaddleTensor,
                                          create_paddle_predictor)

        B = 2
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.data(name="img", shape=[B, 3, 8, 8],
                             dtype="float32")
            c = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                    padding=1, bias_attr=False)
            bn = fluid.layers.batch_norm(c, is_test=True)
            out = fluid.layers.relu(bn)
        rng = np.random.RandomState(0)
        x = rng.rand(B, 3, 8, 8).astype("float32")
        scope = fluid.Scope()
        with tempfile.TemporaryDirectory() as d:
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                # perturb BN stats so folding is non-trivial
                import jax.numpy as jnp

                for n, v in main.global_block().vars.items():
                    if "batch_norm" in n and ("mean" in n or
                                              "variance" in n):
                        arr = np.asarray(scope.find_var(n).raw().array)
                        scope.var(n).get_tensor()._array = jnp.asarray(
                            arr + rng.rand(*arr.shape).astype("float32")
                            * 0.3 + 0.1)
                (ref,) = exe.run(main, feed={"img": x},
                                 fetch_list=[out])
                fluid.io.save_inference_model(d, ["img"], [out], exe,
                                              main_program=main)
            config = AnalysisConfig(d)
            config.disable_gpu()
            p_opt = create_paddle_predictor(config)
            types = [op.type for op in
                     p_opt._program.global_block().ops]
            assert "batch_norm" not in types, types  # folded
            (got,) = p_opt.run([PaddleTensor(x, name="img")])
            np.testing.assert_allclose(got.as_ndarray(),
                                       np.asarray(ref), rtol=1e-4,
                                       atol=1e-5)

            # switch_ir_optim(False) keeps the raw graph
            config2 = AnalysisConfig(d)
            config2.disable_gpu()
            config2.switch_ir_optim(False)
            p_raw = create_paddle_predictor(config2)
            types2 = [op.type for op in
                      p_raw._program.global_block().ops]
            assert "batch_norm" in types2
            (got2,) = p_raw.run([PaddleTensor(x, name="img")])
            np.testing.assert_allclose(got2.as_ndarray(),
                                       np.asarray(ref), rtol=1e-4,
                                       atol=1e-5)
