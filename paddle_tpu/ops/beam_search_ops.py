"""LoD beam-search ops for the fluid-era seq2seq API.

Parity: /root/reference/paddle/fluid/operators/beam_search_op.cc +
math/beam_search.cc (SelectTopBeamSizeItems :215, PruneEndBeams :140,
output LoD fill :69-92) and beam_search_decode_op.h (Backtrace :143).

TPU-native stance: these are intrinsically ragged, host-side ops — the
LoD bookkeeping is O(batch*beam) scalar work per step while all FLOPs
(scoring the vocabulary) stay in compiled programs upstream. The dense,
whole-program-compiled decoder lives in layers/rnn.py
(BeamSearchDecoder/dynamic_decode + the gather_tree op); this pair
exists so reference machine-translation programs (book
test_machine_translation.py) run unchanged.
"""
from __future__ import annotations

import numpy as np

from ..core.registry import In, Out, register_host_op


def _abs_lod(lod):
    return [list(level) for level in lod]


def _select_top_beam(pre_ids, pre_scores, ids, scores, high_level,
                     beam_size, end_id, is_accumulated):
    """math/beam_search.cc:215 — per source sentence, the top beam_size
    (offset, id, score) items over all its prefix rows; finished prefixes
    (pre_id == end_id) contribute the single item (end_id, pre_score)."""
    # flat indexing exactly like the reference kernel (pre_ids may arrive
    # [rows, 1] or [1, rows]; data walks in row order either way)
    flat_pre_ids = pre_ids.reshape(-1)
    flat_pre_scores = pre_scores.reshape(-1)
    seq_width = int(np.prod(scores.shape[1:])) if scores.ndim > 1 else 1
    flat_scores = scores.reshape(-1)
    flat_ids = ids.reshape(-1) if ids is not None else None
    result = []
    for seq_id in range(len(high_level) - 1):
        items = []
        for offset in range(high_level[seq_id], high_level[seq_id + 1]):
            pre_id = int(flat_pre_ids[offset])
            pre_score = float(flat_pre_scores[offset])
            if pre_id == end_id:
                items.append((offset, end_id, pre_score))
            else:
                base = offset * seq_width
                for d in range(seq_width):
                    tok = (int(flat_ids[base + d]) if flat_ids is not None
                           else d)
                    s = (float(flat_scores[base + d]) if is_accumulated
                         else pre_score
                         + float(np.log(flat_scores[base + d])))
                    items.append((offset, tok, s))
        # Item::operator<: greater score wins; ties -> smaller offset
        items.sort(key=lambda it: (-it[2], it[0]))
        result.append(items[:beam_size])
    return result


def _prune_end_beams(pre_ids, high_level, per_seq_items, end_id):
    """math/beam_search.cc:140 — drop sources whose every selected item
    AND every pre_id is already end_id (one step after finishing, so the
    end tokens still get written out once)."""
    flat_pre = pre_ids.reshape(-1)
    for seq_id, items in enumerate(per_seq_items):
        finish = True
        for (offset, tok, _s) in items:
            if tok != end_id or int(flat_pre[offset]) != end_id:
                finish = False
                break
        if finish:
            per_seq_items[seq_id] = []
    return per_seq_items


@register_host_op(
    "beam_search",
    inputs=[In("pre_ids", no_grad=True), In("pre_scores", no_grad=True),
            In("ids", dispensable=True, no_grad=True),
            In("scores", no_grad=True)],
    outputs=[Out("selected_ids"), Out("selected_scores"),
             Out("parent_idx", dispensable=True)],
    attrs={"level": 0, "beam_size": 1, "end_id": 0, "is_accumulated": True},
)
def _beam_search(executor, op, scope):
    level = int(op.attrs.get("level", 0))
    beam_size = int(op.attrs["beam_size"])
    end_id = int(op.attrs["end_id"])
    is_accumulated = bool(op.attrs.get("is_accumulated", True))

    pre_ids_t = scope.find_var(op.input("pre_ids")[0]).get_tensor()
    pre_scores_t = scope.find_var(op.input("pre_scores")[0]).get_tensor()
    scores_t = scope.find_var(op.input("scores")[0]).get_tensor()
    ids_names = op.input("ids")
    ids_arr = (scope.find_var(ids_names[0]).get_tensor().numpy()
               if ids_names else None)
    pre_ids = pre_ids_t.numpy()
    pre_scores = pre_scores_t.numpy()
    scores = scores_t.numpy()

    lod = _abs_lod(scores_t.lod() or pre_ids_t.lod())
    if not lod:
        # first step convenience: every row its own source (flat row
        # count — pre_ids may arrive [rows, 1] or [1, rows])
        n = int(pre_ids.size)
        lod = [list(range(n + 1)), list(range(n + 1))]
    high_level = lod[level]

    per_seq = _select_top_beam(pre_ids, pre_scores, ids_arr, scores,
                               high_level, beam_size, end_id, is_accumulated)
    per_seq = _prune_end_beams(pre_ids, high_level, per_seq, end_id)

    # regroup by prefix offset (ToMap), then emit rows in offset order
    num_prefix = high_level[-1]
    by_offset = [[] for _ in range(num_prefix)]
    for items in per_seq:
        for it in items:
            by_offset[it[0]].append(it)

    sel_ids, sel_scores, parent = [], [], []
    low_level = []
    off = 0
    for prefix_idx, items in enumerate(by_offset):
        low_level.append(off)
        for (_o, tok, s) in items:
            sel_ids.append(tok)
            sel_scores.append(s)
            parent.append(prefix_idx)
            off += 1
    low_level.append(off)

    out_lod = [list(high_level), low_level]
    n = len(sel_ids)
    executor._write_var(scope, op.output("selected_ids")[0],
                        np.asarray(sel_ids, "int64").reshape(n, 1),
                        lod=out_lod)
    executor._write_var(scope, op.output("selected_scores")[0],
                        np.asarray(sel_scores, "float32").reshape(n, 1),
                        lod=out_lod)
    pouts = op.output("parent_idx")
    if pouts:
        executor._write_var(scope, pouts[0], np.asarray(parent, "int32"))


@register_host_op(
    "beam_search_decode",
    inputs=[In("Ids", no_grad=True), In("Scores", no_grad=True)],
    outputs=[Out("SentenceIds"), Out("SentenceScores")],
    attrs={"beam_size": 1, "end_id": 0},
)
def _beam_search_decode(executor, op, scope):
    """beam_search_decode_op.h Backtrace: walk the per-step selected
    LoDTensors from last step to first, following each row's prefix via
    the step's sentence-level LoD; emit per-source sentences (reversed at
    the end), skipping redundant trailing end tokens."""
    end_id = int(op.attrs["end_id"])
    ids_arr = scope.find_var(op.input("Ids")[0]).get_lod_tensor_array()
    scores_arr = scope.find_var(op.input("Scores")[0]).get_lod_tensor_array()
    steps = len(ids_arr)
    if steps == 0:
        raise ValueError("beam_search_decode: empty step array")

    src_level, sent_level = 0, 1
    src_num = len(ids_arr[0].lod()[src_level]) - 1
    # per source: list of sentences ([word_ids], [scores]) + prefix index
    sentences = [[] for _ in range(src_num)]
    prefix_idx_vec = [[] for _ in range(src_num)]

    for step_id in range(steps - 1, -1, -1):
        cur_ids = ids_arr[step_id]
        cur_scores = scores_arr[step_id]
        id_data = cur_ids.numpy().reshape(-1)
        sc_data = cur_scores.numpy().reshape(-1)
        lod = cur_ids.lod()
        for src in range(src_num):
            p_start = lod[src_level][src]
            p_end = lod[src_level][src + 1]
            if not prefix_idx_vec[src]:
                # last step (or source pruned at this step): open one
                # sentence per selected row
                for prefix in range(p_start, p_end):
                    c_start = lod[sent_level][prefix]
                    c_end = lod[sent_level][prefix + 1]
                    for cand in range(c_start, c_end):
                        prefix_idx_vec[src].append(prefix)
                        sentences[src].append(
                            ([int(id_data[cand])], [float(sc_data[cand])]))
            else:
                src_cand_start = lod[sent_level][p_start]
                prefix = p_start
                cand_num = (lod[sent_level][prefix + 1]
                            - lod[sent_level][prefix])
                for idx in range(len(prefix_idx_vec[src])):
                    cand = prefix_idx_vec[src][idx]
                    tok = int(id_data[cand])
                    sc = float(sc_data[cand])
                    words, scs = sentences[src][idx]
                    if tok != end_id or not words:
                        words.append(tok)
                        scs.append(sc)
                    while src_cand_start + cand_num <= cand:
                        prefix += 1
                        cand_num += (lod[sent_level][prefix + 1]
                                     - lod[sent_level][prefix])
                    prefix_idx_vec[src][idx] = prefix

    # ConvertSentenceVectorToLodTensor: reversed word order, 2-level LoD
    flat_ids, flat_scores = [], []
    src_lod, sent_lod = [0], [0]
    for src in range(src_num):
        for words, scs in sentences[src]:
            flat_ids.extend(reversed(words))
            flat_scores.extend(reversed(scs))
            sent_lod.append(len(flat_ids))
        src_lod.append(len(sent_lod) - 1)
    out_lod = [src_lod, sent_lod]
    n = len(flat_ids)
    executor._write_var(scope, op.output("SentenceIds")[0],
                        np.asarray(flat_ids, "int64").reshape(n, 1),
                        lod=out_lod)
    executor._write_var(scope, op.output("SentenceScores")[0],
                        np.asarray(flat_scores, "float32").reshape(n, 1),
                        lod=out_lod)
