#!/usr/bin/env python
"""GB-scale parameter-server delta-path measurement (ISSUE 13).

Runs a synthetic SPARSE TABLE (height x width float32; --gb sizes it)
through the REAL replication path — a primary + backup ``PSServer``
pair over localhost sockets, push_sparse touching a few rows per
round — and records the two curves the ROADMAP asks for:

- **digest cost**: milliseconds of blake2b hashing per round, under
  incremental chunk digesting (``PADDLE_PS_INCR_DIGEST=1``, the
  default: only rows/chunks dirtied since the last ship re-hash) vs
  the full re-hash-every-var-every-round baseline (=0). At GB scale
  the full re-hash is the dominant serial cost of a delta round; the
  bench asserts incremental is STRICTLY cheaper.
- **wire savings**: replication bytes per round, delta vs the full
  anchor — a GB table touched on a handful of rows must ship row
  slices, not the table.
- **durable-frame cost** (ISSUE 19): bytes the primary persists to
  the crash-consistent round store per committed round
  (``checkpoint.round_bytes{mode=delta}``) vs the full anchor frame,
  asserted < 1%% of the anchor on the few-rows-touched table — plus a
  measured cold restore of the table from that store, gated
  bit-for-bit against the primary's final state.

Output (--out) is a bench_diff-compatible record::

    {"configs": {"ps_scale": {"table_mb":, "rounds":, "rounds_per_s":,
                              "step_ms":, "ps_digest_ms":,
                              "ps_digest_full_ms":,
                              "repl_delta_bytes_per_round":,
                              "repl_anchor_bytes":,
                              "ckpt_delta_bytes_per_round":,
                              "ckpt_anchor_bytes":,
                              "ckpt_restore_ms":}},
     "counters_total": {...}}

``tools/bench_diff.py`` watches ``ps_digest_ms`` (lower is better),
``ckpt_delta_bytes_per_round`` and ``ckpt_restore_ms``: a change that
silently regresses incremental digesting back toward full re-hashing,
or durable frames back toward whole-table snapshots, fails the perf
gate run-over-run.

Usage: python tools/ps_scale_bench.py [--gb 0.25] [--rows 4]
           [--rounds 6] [--width 256] [--out rec.json] [--smoke]

``--smoke`` shrinks the table to ~16 MB for CI/tests; multi-GB runs
are the manual measurement mode (memory: ~3x the table — primary +
backup + one in-flight copy).
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class MiniScope(dict):
    def local_var_names(self):
        return list(self)


class MiniExec:
    def _read_var(self, scope, name):
        return scope.get(name)

    def _write_var(self, scope, name, val):
        scope[name] = val

    def run_block(self, block, scope):
        block(scope)


def _sparse_block(scope):
    """Row-local sgd, like a pslib sparse optimize block."""
    g = scope["emb@GRAD"]
    rows = np.asarray(g.rows(), dtype=np.int64)
    vals = np.asarray(g._value)
    emb = scope["emb"]
    emb[rows] -= np.float32(0.1) * vals  # in place: rows only


def _mk_pair(eps, height, width, durable_dir=None):
    from paddle_tpu.distributed.ps_rpc import PSServer

    servers = []
    scopes = []
    for ep in eps:
        scope = MiniScope()
        scope["emb"] = np.zeros((height, width), dtype=np.float32)
        s = PSServer(ep, MiniExec(), scope,
                     {"emb@GRAD": _sparse_block}, fanin=1,
                     sync_mode=False, endpoints=eps, lease_ms=0,
                     durable_dir=durable_dir)
        s._async_repl_every = 1  # every push is a replicated round
        s.start_background()
        servers.append(s)
        scopes.append(scope)
    return servers, scopes


def _counter_delta(before, name, **labels):
    from paddle_tpu import observability as obs

    return (obs.counter_value(name, **labels) or 0) - before.get(
        (name, tuple(sorted(labels.items()))), 0)


def _snap(*specs):
    from paddle_tpu import observability as obs

    return {(n, tuple(sorted(ls.items()))): obs.counter_value(n, **ls)
            or 0 for n, ls in specs}


def run_mode(height, width, rows_per_round, rounds, incremental,
             durable_dir=None):
    """One measured pass; returns (digest_ms_per_round,
    delta_bytes_per_round, anchor_bytes, rounds_per_s, ckpt) — ckpt is
    None without ``durable_dir``, else the durable-frame measurements
    {"delta_b", "anchor_b", "restore_ms", "bitwise"} from the
    crash-consistent round store (ISSUE 19), including a timed cold
    restore of the table on a fresh server, gated bit-for-bit."""
    from paddle_tpu.distributed.ps_rpc import PSClient, PSServer

    os.environ["PADDLE_PS_INCR_DIGEST"] = "1" if incremental else "0"
    eps = ["127.0.0.1:%d" % _free_port() for _ in range(2)]
    servers, scopes = _mk_pair(eps, height, width, durable_dir)
    specs = [("ps.digest_ms", {}),
             ("ps.replication_bytes", {"mode": "delta"}),
             ("ps.replication_bytes", {"mode": "full"}),
             ("checkpoint.round_bytes", {"mode": "delta"}),
             ("checkpoint.round_bytes", {"mode": "full"})]
    try:
        c = PSClient(",".join(eps), trainer_id=0)
        rng = np.random.RandomState(7)
        # round 0 primes the anchor (the whole table hashes + ships
        # once either way); the measured window is pure delta rounds
        base0 = _snap(*specs)
        c.push_sparse("emb@GRAD", [0],
                      np.ones((1, width), "f4"), param="emb")
        base = _snap(*specs)
        t0 = time.perf_counter()
        for rnd in range(rounds):
            ids = rng.choice(height, size=rows_per_round,
                             replace=False).astype(np.int64)
            c.push_sparse("emb@GRAD", ids,
                          np.full((rows_per_round, width),
                                  0.5 + rnd, "f4"), param="emb")
        dt = time.perf_counter() - t0
        digest_ms = _counter_delta(base, "ps.digest_ms") / rounds
        delta_b = _counter_delta(base, "ps.replication_bytes",
                                 mode="delta") / rounds
        anchor_b = _counter_delta(base0, "ps.replication_bytes",
                                  mode="full")
        c.close()
        ckpt = None
        if durable_dir:
            ckpt = {
                "delta_b": _counter_delta(
                    base, "checkpoint.round_bytes",
                    mode="delta") / rounds,
                "anchor_b": _counter_delta(
                    base0, "checkpoint.round_bytes", mode="full"),
            }
            final = np.array(scopes[0]["emb"])
            for s in servers:
                s.stop()
            # timed cold restore on a FRESH server: load the newest
            # restorable round (anchor + delta chain) from disk
            scope2 = MiniScope()
            scope2["emb"] = np.zeros((height, width),
                                     dtype=np.float32)
            ep2 = "127.0.0.1:%d" % _free_port()
            os.environ["PADDLE_PS_RESTORE"] = "1"
            try:
                t0r = time.perf_counter()
                s2 = PSServer(ep2, MiniExec(), scope2,
                              {"emb@GRAD": _sparse_block}, fanin=1,
                              sync_mode=False, endpoints=[ep2],
                              lease_ms=0, durable_dir=durable_dir)
                ckpt["restore_ms"] = (time.perf_counter() - t0r) * 1e3
                s2.stop()
            finally:
                os.environ.pop("PADDLE_PS_RESTORE", None)
            ckpt["bitwise"] = (scope2["emb"].tobytes()
                               == final.tobytes())
        return digest_ms, delta_b, anchor_b, rounds / dt, ckpt
    finally:
        for s in servers:
            s.stop()
        os.environ.pop("PADDLE_PS_INCR_DIGEST", None)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--gb", type=float, default=0.25,
                    help="sparse table size in GiB (default 0.25; "
                         "multi-GB for the real measurement)")
    ap.add_argument("--rows", type=int, default=4,
                    help="rows touched per round")
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--width", type=int, default=256)
    ap.add_argument("--out", default=None,
                    help="write the bench_diff-compatible record here")
    ap.add_argument("--smoke", action="store_true",
                    help="~16MB table (CI/tests)")
    args = ap.parse_args(argv)

    gb = 0.015625 if args.smoke else args.gb
    height = max(64, int(gb * (1 << 30)) // (4 * args.width))
    table_mb = height * args.width * 4 / (1 << 20)
    # the anchor interval must exceed the run, or anchors pollute the
    # per-round delta window
    os.environ["PADDLE_PS_ANCHOR_EVERY"] = str(10 * (args.rounds + 2))
    print("[ps_scale] table %.1f MB (%d x %d f32), %d rows/round, "
          "%d rounds" % (table_mb, height, args.width, args.rows,
                         args.rounds))

    durable_dir = tempfile.mkdtemp(prefix="ps_scale_durable_")
    try:
        inc_ms, delta_b, anchor_b, rps, ckpt = run_mode(
            height, args.width, args.rows, args.rounds,
            incremental=True, durable_dir=durable_dir)
        full_ms, delta_b2, _, _, _ = run_mode(
            height, args.width, args.rows, args.rounds,
            incremental=False)
    finally:
        shutil.rmtree(durable_dir, ignore_errors=True)
    print("[ps_scale] digest cost/round: incremental %.2f ms vs full "
          "re-hash %.2f ms (%.1fx)" % (inc_ms, full_ms,
                                       full_ms / max(inc_ms, 1e-9)))
    print("[ps_scale] wire: delta %.1f KB/round vs anchor %.1f MB "
          "(%.4f%%)" % (delta_b / 1024, anchor_b / (1 << 20),
                        100.0 * delta_b / max(anchor_b, 1)))
    print("[ps_scale] durable: delta frame %.1f KB/round vs anchor "
          "frame %.1f MB (%.4f%%), cold restore %.1f ms (bit-for-bit "
          "%s)" % (ckpt["delta_b"] / 1024,
                   ckpt["anchor_b"] / (1 << 20),
                   100.0 * ckpt["delta_b"] / max(ckpt["anchor_b"], 1),
                   ckpt["restore_ms"],
                   "PASS" if ckpt["bitwise"] else "FAIL"))
    print("[ps_scale] %.1f rounds/s (incremental mode)" % rps)

    ok = True
    if full_ms <= inc_ms:
        print("[ps_scale] FAIL: incremental digesting (%.2f ms) not "
              "cheaper than full re-hash (%.2f ms)"
              % (inc_ms, full_ms), file=sys.stderr)
        ok = False
    if not 0 < delta_b < 0.01 * anchor_b:
        print("[ps_scale] FAIL: delta bytes %.0f not under 1%% of "
              "the anchor %.0f" % (delta_b, anchor_b),
              file=sys.stderr)
        ok = False
    if not 0 < ckpt["delta_b"] < 0.01 * ckpt["anchor_b"]:
        print("[ps_scale] FAIL: durable frame bytes %.0f not under "
              "1%% of the anchor frame %.0f"
              % (ckpt["delta_b"], ckpt["anchor_b"]), file=sys.stderr)
        ok = False
    if not ckpt["bitwise"]:
        print("[ps_scale] FAIL: cold restore diverged from the "
              "primary's final table", file=sys.stderr)
        ok = False

    if args.out:
        from paddle_tpu import observability as obs

        rec = {"configs": {"ps_scale": {
            "table_mb": round(table_mb, 2),
            "rounds": args.rounds,
            "rows_per_round": args.rows,
            "rounds_per_s": round(rps, 3),
            "step_ms": round(1e3 / max(rps, 1e-9), 3),
            "ps_digest_ms": round(inc_ms, 4),
            "ps_digest_full_ms": round(full_ms, 4),
            "repl_delta_bytes_per_round": round(delta_b, 1),
            "repl_anchor_bytes": int(anchor_b),
            "ckpt_delta_bytes_per_round": round(ckpt["delta_b"], 1),
            "ckpt_anchor_bytes": int(ckpt["anchor_b"]),
            "ckpt_restore_ms": round(ckpt["restore_ms"], 3),
        }}, "counters_total": {
            k: v for k, v in {
                "ps.delta_rounds": obs.counter_value("ps.delta_rounds"),
                "ps.anchor_rounds": obs.counter_value(
                    "ps.anchor_rounds"),
            }.items() if v}}
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1, sort_keys=True)
        print("[ps_scale] record -> %s" % args.out)
    print("[ps_scale] %s" % ("OK" if ok else "FAIL"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
