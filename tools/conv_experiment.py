"""Pallas implicit-GEMM conv vs XLA conv on ResNet-50 hot shapes.

The round-4 verdict's #1 ask: apply the flash-attention blocking lesson
to the conv stack and measure back-to-back (BASELINE.md gets the table,
win or lose). Run on the real chip:  python tools/conv_experiment.py
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.ops.pallas.conv import conv2d_bn_act

# (H, Cin, Cout, K, stride, pad) — ResNet-50 b128 bottleneck mix
SHAPES = [
    (56, 64, 64, 1, 1, 0),
    (56, 64, 64, 3, 1, 1),
    (56, 64, 256, 1, 1, 0),
    (56, 256, 64, 1, 1, 0),
    (28, 128, 128, 3, 1, 1),
    (28, 512, 128, 1, 1, 0),
    (28, 128, 512, 1, 1, 0),
    (14, 256, 256, 3, 1, 1),
    (14, 1024, 256, 1, 1, 0),
    (14, 256, 1024, 1, 1, 0),
    (7, 512, 512, 3, 1, 1),
    (7, 2048, 512, 1, 1, 0),
    (7, 512, 2048, 1, 1, 0),
    (56, 256, 128, 1, 2, 0),   # stage-3 downsample 1x1
    (28, 128, 128, 3, 2, 1),   # stage-3 first 3x3
]


def xla_conv(x, w, sc, sh, stride, pad, relu=True):
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NHWC", "HWIO", "NHWC"))
    o = lax.conv_general_dilated(
        x, w, (stride, stride), [(pad, pad), (pad, pad)],
        dimension_numbers=dn)
    o = o.astype(jnp.float32) * sc + sh
    if relu:
        o = jnp.maximum(o, 0.0)
    return o.astype(x.dtype)


def timeit(fn, x, iters=30):
    @jax.jit
    def loop(x):
        def body(i, carry):
            s, = carry
            o = fn(x * (1.0 + 0.0 * s).astype(x.dtype))
            return (o.astype(jnp.float32).ravel()[0],)
        return lax.fori_loop(0, iters, body, (jnp.float32(0.0),))

    r = loop(x)
    float(r[0])                     # compile + warm
    best = 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        r = loop(x)
        float(r[0])                 # hard d2h sync
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def main(batch=128, dtype=jnp.bfloat16):
    rng = np.random.RandomState(0)
    print("dev", jax.devices())
    rows = []
    for (H, Cin, Cout, K, s, p) in SHAPES:
        x = jnp.asarray(rng.randn(batch, H, H, Cin), dtype)
        w = jnp.asarray(rng.randn(K, K, Cin, Cout) * 0.05, dtype)
        sc = jnp.asarray(rng.rand(Cout) + 0.5, jnp.float32)
        sh = jnp.asarray(rng.randn(Cout), jnp.float32)

        t_xla = timeit(lambda x: xla_conv(x, w, sc, sh, s, p), x)
        try:
            t_pl = timeit(lambda x: conv2d_bn_act(
                x, w, sc, sh, stride=s, padding=p, relu=True), x)
        except Exception as e:
            t_pl = float("nan")
            print("pallas failed:", type(e).__name__, str(e)[:200])
        Ho = (H + 2 * p - K) // s + 1
        gflop = 2.0 * batch * Ho * Ho * K * K * Cin * Cout / 1e9
        rows.append((H, Cin, Cout, K, s, t_xla * 1e3, t_pl * 1e3,
                     gflop / t_xla / 1e3, gflop / t_pl / 1e3,
                     t_xla / t_pl))
        print("H%3d %4d->%4d k%d s%d | xla %7.3f ms (%6.1f TF/s) | "
              "pallas %7.3f ms (%6.1f TF/s) | speedup %.2fx"
              % (H, Cin, Cout, K, s, t_xla * 1e3, gflop / t_xla / 1e3,
                 t_pl * 1e3, gflop / t_pl / 1e3, t_xla / t_pl))
    tot_x = sum(r[5] for r in rows)
    tot_p = sum(r[6] for r in rows)
    print("TOTAL xla %.3f ms  pallas %.3f ms  speedup %.2fx"
          % (tot_x, tot_p, tot_x / tot_p))


if __name__ == "__main__":
    main()
