"""Device-truth profiling (ISSUE 10): XPlane parsing, phase folding,
host-vs-device cross-check, and the profile-guided bucket planner's
report plumbing.

The parser/folding tests run on CANNED trace fixtures built with the
module's own encoder — no device, no jax.profiler, so they hold in
tier-1 anywhere. The one real end-to-end capture test is slow-marked
(full CI runs it): it proves the jax.profiler -> xplane.pb -> fold
pipeline against a live program.

Contracts under test:
- wire roundtrip: encode_xspace -> parse_xspace preserves planes /
  lines / events / stats / HLO op_name maps;
- phase folding: device op intervals land in their named_scope phase,
  per-phase time is the interval UNION (concurrent thunks counted
  once), collective-vs-compute overlap matches analyze_timeline;
- unknown-scope tolerance: an op resolving to no known phase is
  accounted (unattributed_ms), never dropped silently, never fatal;
- empty-trace fallback: no phase-attributed events => fold returns
  None and callers keep host numbers;
- cross_check: min/max per-phase agreement, duration-weighted overall.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.observability import device_trace as dtr
from paddle_tpu.observability import profiler as prof

MS = 1_000_000_000   # ps per ms


def _plane(events, name="/host:CPU", hlo=None, ts_ns=0):
    return {"name": name, "hlo_op_names": hlo or {},
            "lines": [{"name": "thread0", "timestamp_ns": ts_ns,
                       "events": events}]}


def _ev(name, ts_ms, dur_ms, stats=None):
    return {"name": name, "ts_ps": int(ts_ms * MS),
            "dur_ps": int(dur_ms * MS), "stats": stats or {}}


# -- wire roundtrip ---------------------------------------------------------


def test_encode_parse_roundtrip():
    space = {"planes": [{
        "name": "/host:CPU",
        "hlo_op_names": {"fusion.1": "jit(f)/jit(main)/forward/mul/dot",
                         "reduce.9": "jit(f)/jit(main)/backward/sum"},
        "lines": [{"name": "t0", "timestamp_ns": 1000, "events": [
            {"name": "fusion.1", "ts_ps": 1_500_000, "dur_ps": 250,
             "stats": {"hlo_op": "fusion.1"}},
            {"name": "reduce.9", "ts_ps": 2_000_000, "dur_ps": 40,
             "stats": {}},
        ]}],
    }]}
    got = dtr.parse_xspace(dtr.encode_xspace(space))
    assert len(got["planes"]) == 1
    pl = got["planes"][0]
    assert pl["name"] == "/host:CPU"
    assert pl["hlo_op_names"] == space["planes"][0]["hlo_op_names"]
    (line,) = pl["lines"]
    assert line["timestamp_ns"] == 1000
    evs = line["events"]
    assert [e["name"] for e in evs] == ["fusion.1", "reduce.9"]
    assert evs[0]["ts_ps"] == 1_500_000
    assert evs[0]["dur_ps"] == 250
    assert evs[0]["stats"] == {"hlo_op": "fusion.1"}


def test_parse_rejects_garbage_tolerates_unknown_fields():
    with pytest.raises((ValueError, IndexError)):
        dtr.parse_xspace(b"\x99\x99not a proto")
    # unknown fields inside a plane are skipped, known ones survive
    plane = dtr._enc_len(2, b"p") + dtr._enc_int(9, 7) \
        + dtr._enc_len(15, b"future-field")
    space = dtr._enc_len(1, plane)
    got = dtr.parse_xspace(space)
    assert got["planes"][0]["name"] == "p"


# -- phase resolution -------------------------------------------------------


def test_phase_of_op_name():
    assert dtr.phase_of_op_name(
        "jit(step)/jit(main)/backward/mul_grad/dot_general") == "backward"
    assert dtr.phase_of_op_name(
        "jit(s)/jit(main)/jit(shmap_body)/collective/c_bucket_allreduce"
        "/psum") == "collective"
    assert dtr.phase_of_op_name("forward/mul") == "forward"
    assert dtr.phase_of_op_name("jit(f)/jit(main)/reduce_sum") is None
    assert dtr.phase_of_op_name("") is None
    assert dtr.phase_of_op_name(None) is None


# -- folding on canned fixtures ---------------------------------------------


def test_fold_phases_from_hlo_map_and_direct_names():
    hlo = {"fusion.1": "jit(f)/jit(main)/forward/mul/dot",
           "fusion.2": "jit(f)/jit(main)/backward/mul_grad/dot",
           "ar.1": "jit(f)/jit(main)/collective/c_bucket_allreduce/psum"}
    space = {"planes": [_plane([
        _ev("fusion.1", 0.0, 2.0),            # forward, via name->hlo
        _ev("thunk", 2.0, 3.0,                # backward, via hlo_op stat
            stats={"hlo_op": "fusion.2"}),
        _ev("ar.1", 3.0, 2.0),                # collective, overlaps bwd
        _ev("optimizer/sgd", 5.0, 1.0),       # direct phase-named event
    ], hlo=hlo)]}
    rep = dtr.fold_device_phases(space)
    assert rep is not None
    assert rep["n_attributed"] == 4
    pm = rep["device_phase_ms"]
    assert pm["forward"] == pytest.approx(2.0)
    assert pm["backward"] == pytest.approx(3.0)
    assert pm["collective"] == pytest.approx(2.0)
    assert pm["optimizer"] == pytest.approx(1.0)
    # collective [3,5] vs compute union [0,5]+[5,6]: fully overlapped
    assert rep["overlap_frac"] == pytest.approx(1.0)
    assert rep["exposed_collective_ms"] == pytest.approx(0.0)
    assert rep["critical_path_ms"] == pytest.approx(6.0)


def test_fold_union_not_sum_across_lines():
    # the same 2ms window busy on TWO lines (concurrent thunks) must
    # count once in the phase's device time
    hlo = {"f.1": "jit(f)/forward/mul"}
    space = {"planes": [{
        "name": "/host:CPU", "hlo_op_names": hlo,
        "lines": [
            {"name": "t0", "timestamp_ns": 0,
             "events": [_ev("f.1", 0.0, 2.0)]},
            {"name": "t1", "timestamp_ns": 0,
             "events": [_ev("f.1", 1.0, 2.0)]},
        ]}]}
    rep = dtr.fold_device_phases(space)
    assert rep["device_phase_ms"]["forward"] == pytest.approx(3.0)


def test_fold_unknown_scope_tolerated_and_accounted():
    hlo = {"f.1": "jit(f)/forward/mul",
           "mystery.1": "jit(f)/jit(main)/some_new_scope/op"}
    space = {"planes": [_plane([
        _ev("f.1", 0.0, 1.0),
        _ev("mystery.1", 1.0, 5.0),       # known op, unknown scope
        _ev("ThunkExecutor::Execute", 0.0, 9.0),   # host machinery
    ], hlo=hlo)]}
    rep = dtr.fold_device_phases(space)
    assert rep["n_attributed"] == 1
    assert rep["device_phase_ms"] == {"forward": pytest.approx(1.0)}
    # the unknown-scope op is accounted; the unresolvable host event
    # is ignored (it is not a device op)
    assert rep["unattributed_ms"] == pytest.approx(5.0)


def test_fold_empty_trace_falls_back_to_none():
    assert dtr.fold_device_phases({"planes": []}) is None
    # events exist but none resolve to a phase -> still None
    space = {"planes": [_plane([_ev("PjitFunction(f)", 0.0, 1.0)])]}
    assert dtr.fold_device_phases(space) is None


def test_fold_divides_by_steps():
    hlo = {"f.1": "jit(f)/forward/mul"}
    space = {"planes": [_plane(
        [_ev("f.1", 0.0, 2.0), _ev("f.1", 10.0, 2.0)], hlo=hlo)]}
    rep = dtr.fold_device_phases(space, steps=2)
    assert rep["device_phase_ms"]["forward"] == pytest.approx(2.0)
    assert rep["steps"] == 2


def test_fixture_file_roundtrip_via_trace_dir(tmp_path):
    # the on-disk layout jax.profiler writes: the fold must find the
    # newest run dir's xplane.pb
    run = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    run.mkdir(parents=True)
    hlo = {"f.1": "jit(f)/forward/mul"}
    space = {"planes": [_plane([_ev("f.1", 0.0, 4.0)], hlo=hlo)]}
    (run / "host.xplane.pb").write_bytes(dtr.encode_xspace(space))
    (run / "garbage.xplane.pb").write_bytes(b"\xff\xff torn capture")
    loaded = dtr.load_trace_dir(str(tmp_path))
    rep = dtr.fold_device_phases(loaded)
    assert rep["device_phase_ms"]["forward"] == pytest.approx(4.0)


# -- cross-check ------------------------------------------------------------


def test_cross_check_agreement_math():
    cc = dtr.cross_check({"forward": 2.0, "backward": 4.0},
                         {"forward": 2.0, "backward": 4.0})
    assert cc["agreement"] == pytest.approx(1.0)
    assert all(v["agreement"] == pytest.approx(1.0)
               for v in cc["per_phase"].values())
    # device half of host on one phase: ratio 0.5, weighted by the
    # larger side (4ms) against the perfectly-agreeing 2ms phase
    cc = dtr.cross_check({"forward": 2.0, "backward": 4.0},
                         {"forward": 2.0, "backward": 2.0})
    assert cc["per_phase"]["backward"]["agreement"] == pytest.approx(0.5)
    assert cc["agreement"] == pytest.approx((1.0 * 2 + 0.5 * 4) / 6)
    # a phase missing on one side scores 0 for that phase
    cc = dtr.cross_check({"optimizer": 3.0}, {})
    assert cc["per_phase"]["optimizer"]["agreement"] == 0.0
    assert cc["agreement"] == pytest.approx(0.0)
    assert dtr.cross_check({}, {})["agreement"] is None


def test_capture_enabled_env(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_DEVICE_TRACE", raising=False)
    assert not dtr.capture_enabled()
    assert dtr.capture_enabled(default=True)
    monkeypatch.setenv("PADDLE_TPU_DEVICE_TRACE", "1")
    assert dtr.capture_enabled()
    monkeypatch.setenv("PADDLE_TPU_DEVICE_TRACE", "0")
    assert not dtr.capture_enabled(default=True)


# -- end-to-end capture (real jax.profiler) ---------------------------------


def _small_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.data(name="dx", shape=[16, 8], dtype="float32")
        lbl = fluid.data(name="dlbl", shape=[16, 1], dtype="int64")
        h = fluid.layers.fc(x, size=32, act="relu")
        pred = fluid.layers.fc(h, size=10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, lbl))
        fluid.optimizer.MomentumOptimizer(0.1, 0.9).minimize(loss)
    return main, startup, loss


@pytest.mark.slow
def test_device_profile_step_end_to_end(tmp_path):
    main, startup, loss = _small_program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"dx": rng.rand(16, 8).astype("float32"),
                "dlbl": rng.randint(0, 10, (16, 1)).astype("int64")}
        exe.run(main, feed=feed, fetch_list=[loss])
        assert not prof.annotating()   # default off before...
        dev = dtr.device_profile_step(main, scope, feed, steps=2,
                                      trace_dir=str(tmp_path))
        assert not prof.annotating()   # ...and restored after
    assert dev is not None, "real capture folded to empty"
    assert dev["n_attributed"] > 0
    assert set(dev["device_phase_ms"]) <= set(dtr.PHASES)
    assert all(ms >= 0 for ms in dev["device_phase_ms"].values())
    assert dev["critical_path_ms"] > 0
    # the raw capture really is on disk where TensorBoard would read it
    assert dtr.find_xplane_files(str(tmp_path))


@pytest.mark.slow
def test_bench_profile_record_carries_device_block(monkeypatch,
                                                   tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    main, startup, loss = _small_program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"dx": rng.rand(16, 8).astype("float32"),
                "dlbl": rng.randint(0, 10, (16, 1)).astype("int64")}
        exe.run(main, feed=feed, fetch_list=[loss])
        monkeypatch.setenv("PADDLE_TPU_PROFILE_BENCH", "1")
        monkeypatch.setenv("PADDLE_TPU_DEVICE_TRACE", "1")
        rec = bench._profile_record(0.01, 1e9, program=main,
                                    scope=scope, feed=feed)
    assert "phase_ms" in rec, rec.get("phase_error")
    assert "device_trace_error" not in rec, rec["device_trace_error"]
    # both breakdowns + the agreement ratio ride one record
    assert rec.get("device_phase_ms")
    assert rec.get("host_device_agreement") is not None
    assert rec.get("agreement_per_phase")
    assert json.dumps(rec)   # the whole block is json-serializable
