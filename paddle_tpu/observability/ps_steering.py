"""PS hot-shard steerer: apply-time skew -> a ``migrate_range`` plan.

The data-placement sibling of the placement steerer (PAPERS.md's
placement-synthesis loop applied to rows instead of compute): the PS
labels every sparse apply with ``ps.apply_ms{shard=,table=}`` and a
coarse ``ps.row_heat{shard=,table=,bucket=}`` census; this module
turns a sustained per-shard skew in those histograms into a PROPOSED
row-range move — the hottest boundary-aligned slice of the hottest
table on the hottest shard, re-homed to the coldest shard.

Wiring (the PR-16 discipline, nothing applied here):

- ``apply_skew_value(...)`` is a ``WatchRule`` extractor over the
  merged ``metrics.json`` — max/min ratio of per-shard mean apply
  time, ``None`` until at least two shards reported past a count
  floor;
- the registered ``ps_migrate_range`` steerer re-derives the hot
  shard/table and the split point from the SAME merged document and
  returns the plan dict ``{"kind": "migrate_range", "table", "lo",
  "hi", "from_shard", "to_shard", "height"}``;
- application is ``observability/canary.py``'s job: its ``apply_fn``
  calls the live ``ShardedPSClient.migrate_range`` so the proposal
  rides the real freeze/install/commit protocol, and promotion or
  rollback lands in the ``PlanStore`` audit trail like every other
  steering decision.

Split-point derivation is deliberately coarse: the server buckets
row heat into 8 equal slices of ITS OWN table slice (the census is
local — a shard never knows the global partition), so candidate
splits are the donor span's own bucket edges (``migrate_range``
refuses ranges crossing ownership boundaries anyway). The steerer
picks the edge that best isolates the hot side, and moves THAT side.

Two skew signals feed the same steerer:

- ``apply_skew_value`` — wall-time skew of per-shard round apply
  means. The production signal (it sees CPU cost a row count can't),
  but noisy on small workloads;
- ``row_load_skew_value`` — per-shard row-touch skew from the
  ``ps.row_heat`` counters. Deterministic for a deterministic
  workload, which is what a seeded CI drill needs
  (``row_load_rule``); production rules may combine both.

Both extractors are WINDOWED-FIRST since ISSUE 20: when the merged
doc carries ``series_windows`` (observability/timeseries.py rings
folded by ``merge_job_dir``), the skew is computed over the LAST
WINDOW's deltas — "hot over the last few dump ticks", not "hot since
process start" — with the lifetime counter/histogram path kept as a
bit-identical fallback for docs without series.
"""
from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Tuple

from . import steering

__all__ = ["apply_skew_value", "shard_apply_means", "table_heat",
           "shard_row_load", "row_load_skew_value",
           "windowed_shard_row_load", "windowed_shard_apply_means",
           "propose_migrate_range", "hot_shard_rule",
           "row_load_rule", "STEERER_NAME", "HEAT_BUCKETS"]

STEERER_NAME = "ps_migrate_range"
HEAT_BUCKETS = 8


def _parse_labels(qualified: str) -> Tuple[str, Dict[str, str]]:
    """``name{k=v,...}`` -> (name, labels). Bare names get {}."""
    if "{" not in qualified or not qualified.endswith("}"):
        return qualified, {}
    name, body = qualified.split("{", 1)
    labels = {}
    for part in body[:-1].split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            labels[k] = v
    return name, labels


def _iter_histograms(doc: Dict, family: str):
    """Yield (labels, snapshot) for every per-process histogram of
    ``family`` in a merged metrics.json (histograms are per-process —
    only counters are pre-totaled by the merge)."""
    for sec in (doc.get("processes") or {}).values():
        hists = ((sec.get("metrics") or {}).get("histograms")) or {}
        for qn, snap in hists.items():
            name, labels = _parse_labels(qn)
            if name == family and isinstance(snap, dict):
                yield labels, snap


def shard_apply_means(doc: Dict, table: str = "_round",
                      min_count: int = 1) -> Dict[int, float]:
    """{shard: mean apply ms} for one table's series, sum/count folded
    across processes (a primary and the backup it failed over from
    both dumped — their observations are one shard's story)."""
    sums: Dict[int, float] = {}
    counts: Dict[int, float] = {}
    for labels, snap in _iter_histograms(doc, "ps.apply_ms"):
        if labels.get("table") != table or "shard" not in labels:
            continue
        try:
            shard = int(labels["shard"])
        except ValueError:
            continue
        c = snap.get("count") or 0
        s = snap.get("sum") or 0.0
        if isinstance(c, (int, float)) and c > 0:
            sums[shard] = sums.get(shard, 0.0) + float(s)
            counts[shard] = counts.get(shard, 0.0) + float(c)
    return {sh: sums[sh] / counts[sh] for sh in sums
            if counts.get(sh, 0) >= min_count}


def windowed_shard_apply_means(doc: Dict, table: str = "_round",
                               min_count: int = 1) -> Dict[int, float]:
    """{shard: mean apply ms OVER THE LAST WINDOW} from the merged
    ``series_windows`` (timeseries.py ships each ``ps.apply_ms``
    histogram as a monotone ``#sum``/``#count`` pair, so the windowed
    mean is delta(sum)/delta(count)). Empty when no series exist —
    callers fall back to the lifetime ``shard_apply_means``."""
    wins = doc.get("series_windows")
    if not isinstance(wins, dict):
        return {}
    sums: Dict[int, float] = {}
    counts: Dict[int, float] = {}
    for qn, win in wins.items():
        if not qn.endswith("#sum") or not isinstance(win, dict):
            continue
        name, labels = _parse_labels(qn[:-len("#sum")])
        if name != "ps.apply_ms" or labels.get("table") != table \
                or "shard" not in labels:
            continue
        cwin = wins.get(qn[:-len("#sum")] + "#count")
        if not isinstance(cwin, dict):
            continue
        ds, dc = win.get("delta"), cwin.get("delta")
        if not isinstance(ds, (int, float)) \
                or not isinstance(dc, (int, float)) or dc <= 0:
            continue
        try:
            shard = int(labels["shard"])
        except ValueError:
            continue
        sums[shard] = sums.get(shard, 0.0) + float(ds)
        counts[shard] = counts.get(shard, 0.0) + float(dc)
    return {sh: sums[sh] / counts[sh] for sh in sums
            if counts.get(sh, 0) >= min_count}


def _skew_ratio(per_shard: Dict[int, float]) -> Optional[float]:
    if len(per_shard) < 2:
        return None
    lo, hi = min(per_shard.values()), max(per_shard.values())
    if lo <= 0:
        return None
    return hi / lo


def apply_skew_value(table: str = "_round", min_count: int = 4,
                     ) -> Callable[[Dict], Optional[float]]:
    """WatchRule extractor: max/min ratio of per-shard mean apply time
    (>= 1.0; 1.0 = perfectly balanced). None until two shards have
    each observed ``min_count`` applies — skew over one shard or over
    a handful of samples is noise, not a migration signal.

    Windowed-first (ISSUE 20): when the merged doc carries
    ``series_windows`` with enough samples, the skew is computed over
    the LAST WINDOW's apply means — a shard that went hot five minutes
    ago reads hot now, instead of being averaged against hours of
    balanced history. Docs without series (old dumps, sampling off)
    take the lifetime path unchanged."""
    def _get(doc):
        skew = _skew_ratio(windowed_shard_apply_means(
            doc, table=table, min_count=min_count))
        if skew is not None:
            return skew
        return _skew_ratio(shard_apply_means(doc, table=table,
                                             min_count=min_count))
    return _get


def table_heat(doc: Dict, shard: int) -> Dict[str, List[float]]:
    """{table: [heat per bucket]} for one shard, summed over the
    pre-totaled ``ps.row_heat{...}`` counters."""
    totals = doc.get("counters_total") or {}
    out: Dict[str, List[float]] = {}
    for qn, v in totals.items():
        name, labels = _parse_labels(qn)
        if name != "ps.row_heat" or not isinstance(v, (int, float)):
            continue
        if labels.get("shard") != str(shard):
            continue
        t = labels.get("table")
        try:
            b = int(labels.get("bucket", ""))
        except ValueError:
            continue
        if not t or not (0 <= b < HEAT_BUCKETS):
            continue
        out.setdefault(t, [0.0] * HEAT_BUCKETS)[b] += float(v)
    return out


def shard_row_load(doc: Dict,
                   table: Optional[str] = None) -> Dict[int, float]:
    """{shard: total row touches} from the pre-totaled ``ps.row_heat``
    counters, optionally restricted to one table. Counters, so the
    value is bit-deterministic for a deterministic workload — the
    skew signal the seeded chaos drill gates on."""
    totals = doc.get("counters_total") or {}
    out: Dict[int, float] = {}
    for qn, v in totals.items():
        name, labels = _parse_labels(qn)
        if name != "ps.row_heat" or not isinstance(v, (int, float)):
            continue
        if table is not None and labels.get("table") != table:
            continue
        try:
            shard = int(labels.get("shard", ""))
        except ValueError:
            continue
        out[shard] = out.get(shard, 0.0) + float(v)
    return out


def windowed_shard_row_load(doc: Dict, table: Optional[str] = None
                            ) -> Dict[int, float]:
    """{shard: row touches OVER THE LAST WINDOW} from the merged
    ``series_windows`` deltas of the ``ps.row_heat`` counters. Empty
    when no series exist — callers fall back to the lifetime
    ``shard_row_load``."""
    wins = doc.get("series_windows")
    if not isinstance(wins, dict):
        return {}
    out: Dict[int, float] = {}
    for qn, win in wins.items():
        if not isinstance(win, dict):
            continue
        name, labels = _parse_labels(qn)
        if name != "ps.row_heat" \
                or not isinstance(win.get("delta"), (int, float)):
            continue
        if table is not None and labels.get("table") != table:
            continue
        try:
            shard = int(labels.get("shard", ""))
        except ValueError:
            continue
        out[shard] = out.get(shard, 0.0) + float(win["delta"])
    return out


def row_load_skew_value(table: Optional[str] = None,
                        min_rows: int = 8,
                        ) -> Callable[[Dict], Optional[float]]:
    """WatchRule extractor: max/min ratio of per-shard row touches
    (>= 1.0). None until two shards have each absorbed ``min_rows``
    touches — same noise discipline as ``apply_skew_value``, but over
    counters, so a seeded workload yields a seeded signal.

    Windowed-first (ISSUE 20): with merged ``series_windows``
    present, the ratio is over the LAST WINDOW's row touches (skew
    since the last few dump ticks, not since process start); the
    ``min_rows`` floor then applies per window. Lifetime fallback is
    bit-identical for docs without series."""
    def _get(doc):
        wload = {s: v
                 for s, v in windowed_shard_row_load(doc,
                                                     table).items()
                 if v >= min_rows}
        skew = _skew_ratio(wload)
        if skew is not None:
            return skew
        load = {s: v for s, v in shard_row_load(doc, table).items()
                if v >= min_rows}
        return _skew_ratio(load)
    return _get


def _read_merged(metrics_dir: Optional[str]) -> Optional[Dict]:
    from . import distributed as _dist

    d = metrics_dir or os.environ.get("PADDLE_TPU_METRICS_DIR",
                                      "").strip()
    if not d:
        return None
    try:
        with open(os.path.join(d, _dist.MERGED_METRICS_NAME), "r",
                  encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def propose_migrate_range(report=None, *, doc: Optional[Dict] = None,
                          metrics_dir: Optional[str] = None,
                          height: Optional[int] = None,
                          nshards: Optional[int] = None,
                          min_count: int = 4,
                          by: str = "apply_ms") -> Dict:
    """The ``ps_migrate_range`` steerer body (``report`` is the shared
    steerer signature's profile slot — unused here, the PS signal
    lives in the merged metrics, not a step profile).

    Derivation: hot shard = max mean ``ps.apply_ms{table=_round}``
    (``by="apply_ms"``) or max ``ps.row_heat`` row touches
    (``by="row_heat"`` — deterministic, the seeded drill's choice);
    cold shard = min; hot table = the series that carries the most of
    that signal on the hot shard; split = the heat-bucket edge inside
    the hot shard's span that best separates hot rows from cold,
    moving the hotter side. Raises ``ValueError`` when the telemetry
    cannot support a plan (the daemon records that as a
    propose_error, exactly like a placement search that found
    nothing)."""
    if doc is None:
        doc = _read_merged(metrics_dir)
    if not isinstance(doc, dict):
        raise ValueError("no merged metrics document to steer from")
    if by not in ("apply_ms", "row_heat"):
        raise ValueError("by must be 'apply_ms' or 'row_heat', got %r"
                         % (by,))

    means = shard_apply_means(doc, table="_round",
                              min_count=min_count)
    if by == "row_heat":
        load = shard_row_load(doc)
        if len(load) < 2:
            raise ValueError("need >= 2 shards with row-heat "
                             "counters, have %d" % len(load))
        hot = max(load, key=lambda s: load[s])
        cold = min(load, key=lambda s: load[s])
        skew = (load[hot] / load[cold] if load.get(cold) else None)
    else:
        if len(means) < 2:
            raise ValueError("need >= 2 shards with apply timings, "
                             "have %d" % len(means))
        hot = max(means, key=lambda s: means[s])
        cold = min(means, key=lambda s: means[s])
        skew = (means[hot] / means[cold] if means.get(cold) else None)
    if hot == cold:
        raise ValueError("no per-shard skew to steer on")

    if by == "row_heat":
        # the hot TABLE on the hot shard by row touches
        per_table = {t: sum(h) for t, h in table_heat(doc, hot).items()
                     if sum(h) > 0}
    else:
        # ... by per-table apply time (skip the synthetic whole-round
        # series): the move must name real rows of a real table
        per_table = {}
        for labels, snap in _iter_histograms(doc, "ps.apply_ms"):
            t = labels.get("table")
            if labels.get("shard") != str(hot) or not t \
                    or t == "_round":
                continue
            c, s = snap.get("count") or 0, snap.get("sum") or 0.0
            if isinstance(c, (int, float)) and c > 0:
                per_table[t] = per_table.get(t, 0.0) + float(s)
    if not per_table:
        raise ValueError("hot shard %d has no per-table %s series"
                         % (hot, by))
    table = max(per_table, key=lambda t: per_table[t])

    if nshards is None:
        nshards = len(means)
    if height is None:
        # widest table_rows gauge for this table across shards: the
        # sharded client stamps the GLOBAL height on every push
        best = 0
        totals = doc.get("processes") or {}
        for sec in totals.values():
            gauges = ((sec.get("metrics") or {}).get("gauges")) or {}
            for qn, v in gauges.items():
                name, labels = _parse_labels(qn)
                if name == "ps.table_rows" \
                        and labels.get("table") == table \
                        and isinstance(v, (int, float)):
                    best = max(best, int(v))
        height = best
    if not height or height < nshards:
        raise ValueError("cannot size table %r (height=%r)"
                         % (table, height))

    from ..distributed.ps_shard import row_range

    span_lo, span_hi = row_range(hot, int(height), int(nshards))
    if span_hi - span_lo < 2:
        raise ValueError("hot shard %d's span [%d,%d) is too narrow "
                         "to split" % (hot, span_lo, span_hi))

    heat = (table_heat(doc, hot).get(table)
            or [1.0] * HEAT_BUCKETS)
    # the server's heat census buckets over ITS OWN slice (it never
    # knows the global partition), so bucket b of the donor covers
    # the donor-span rows [span_lo + b*len/8, span_lo + (b+1)*len/8)
    # — edges and side heat both map through the span, not the table
    span_len = span_hi - span_lo
    edges = sorted({
        e for b in range(1, HEAT_BUCKETS)
        for e in (span_lo + (b * span_len + HEAT_BUCKETS - 1)
                  // HEAT_BUCKETS,)
        if span_lo < e < span_hi})
    if not edges:
        edges = [(span_lo + span_hi) // 2]

    def _side_heat(lo: int, hi: int) -> float:
        tot = 0.0
        for b, hv in enumerate(heat):
            blo = span_lo + b * span_len // HEAT_BUCKETS
            bhi = span_lo + (b + 1) * span_len // HEAT_BUCKETS
            ov = max(0, min(hi, bhi) - max(lo, blo))
            if ov > 0 and bhi > blo:
                tot += hv * ov / (bhi - blo)
        return tot

    # pick the edge maximizing heat-per-row contrast between the two
    # sides, then move the hotter side off the hot shard
    best_edge, best_lo, best_hi, best_score = None, None, None, -1.0
    for e in edges:
        for lo, hi in ((span_lo, e), (e, span_hi)):
            rows = hi - lo
            rest = (span_hi - span_lo) - rows
            if rows <= 0 or rest <= 0:
                continue
            score = _side_heat(lo, hi) / rows \
                - _side_heat(*((e, span_hi) if lo == span_lo
                               else (span_lo, e))) / rest
            if score > best_score:
                best_edge, best_lo, best_hi = e, lo, hi
                best_score = score
    if best_lo is None:
        best_lo, best_hi = span_lo, (span_lo + span_hi) // 2

    return {
        "kind": "migrate_range",
        "table": table,
        "lo": int(best_lo),
        "hi": int(best_hi),
        "from_shard": int(hot),
        "to_shard": int(cold),
        "height": int(height),
        "nshards": int(nshards),
        "by": by,
        "skew": round(skew, 4) if skew else None,
        "shard_apply_ms": {str(s): round(v, 4)
                           for s, v in sorted(means.items())},
    }


def hot_shard_rule(threshold: float = 0.5, floor: float = 0.25,
                   min_count: int = 4):
    """The daemon-side ``WatchRule`` for this steerer: per-shard apply
    skew rising past ``threshold`` (relative to the rule's own
    baseline, past an absolute ``floor``) re-runs the steerer. Late
    import keeps module import order loose (the daemon imports THIS
    module through ``_import_consumers``)."""
    from .steering_daemon import WatchRule

    return WatchRule("ps_apply_skew",
                     apply_skew_value(min_count=min_count),
                     direction=-1, threshold=threshold, floor=floor,
                     steerer=STEERER_NAME,
                     description="per-shard PS apply-time skew "
                                 "(max/min mean ratio)")


def row_load_rule(threshold: float = 0.5, floor: float = 0.25,
                  min_rows: int = 8,
                  table: Optional[str] = None):
    """The counter twin of ``hot_shard_rule``: per-shard row-touch
    skew. Deterministic for a seeded workload — the CI chaos drill's
    rule (a wall-time rule under CI jitter flickers on which shard
    reads hot; row counters cannot)."""
    from .steering_daemon import WatchRule

    return WatchRule("ps_row_load_skew",
                     row_load_skew_value(table=table,
                                         min_rows=min_rows),
                     direction=-1, threshold=threshold, floor=floor,
                     steerer=STEERER_NAME,
                     description="per-shard PS row-touch skew "
                                 "(max/min ps.row_heat ratio)")


steering.register_steerer(
    STEERER_NAME, propose_migrate_range,
    description="hot-shard row-range rebalance: apply-time skew + "
                "row heat -> a migrate_range plan")
