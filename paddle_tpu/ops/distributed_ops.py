"""PS-mode distributed ops: send / recv / barriers / listen_and_serv.

Parity: /root/reference/paddle/fluid/operators/distributed_ops/
(send_op.cc, recv_op.cc, listen_and_serv_op.cc:110 RunSyncLoop). The
reference runs these over gRPC between processes; here local endpoints
are served by an IN-PROCESS emulated server registry — listen_and_serv
registers its optimize sub-blocks, send routes a grad to the matching
sub-block and runs it, recv copies the updated param back. That makes
transpiled trainer+pserver programs runnable (and testable) in one
process, the scope the reference covers with test_dist_transpiler plus
localhost subprocesses. Multi-host TPU jobs use the collective fleet
(ICI allreduce) instead of PS — see SURVEY §2.5.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ..core.registry import In, Out, register_host_op

# endpoint -> dict(executor=, scope=, grad_to_block=, program=)
_EMULATED_SERVERS: Dict[str, dict] = {}


def reset_emulated_servers():
    _EMULATED_SERVERS.clear()
    # drop cached RPC client sockets too: a fresh server on a reused
    # endpoint must not inherit a dead connection
    from ..distributed.ps_rpc import PSClient

    PSClient.reset()


@register_host_op(
    "listen_and_serv",
    inputs=[In("X", duplicable=True, dispensable=True, no_grad=True)],
    outputs=[],
    attrs={"endpoint": "", "optimize_blocks": [], "grad_to_block_id": [],
           "sync_mode": True, "Fanin": 1},
)
def _listen_and_serv(executor, op, scope):
    """Register this endpoint's server.

    Two transports: the in-process emulation (default — non-blocking,
    sends drive the optimize blocks synchronously), and a real TCP RPC
    server when PADDLE_PSERVER_RPC=1 (distributed/ps_rpc.py), which
    BLOCKS serving the RunSyncLoop round protocol until a shutdown
    message arrives — the reference listen_and_serv_op.cc behavior."""
    import os

    grad_to_block = {}
    blocks = op.attrs.get("optimize_blocks", [])
    for entry in op.attrs.get("grad_to_block_id", []):
        gname, bid = entry.rsplit(":", 1)
        for b in blocks:
            if b.idx == int(bid):
                grad_to_block[gname] = b
    if os.environ.get("PADDLE_PSERVER_RPC") == "1":
        from ..distributed.ps_rpc import PSServer

        server = PSServer(op.attrs["endpoint"], executor, scope,
                          grad_to_block,
                          fanin=int(op.attrs.get("Fanin", 1)),
                          sync_mode=bool(op.attrs.get("sync_mode", True)))
        server.serve_forever()
        return
    _EMULATED_SERVERS[op.attrs["endpoint"]] = {
        "executor": executor,
        "scope": scope,
        "grad_to_block": grad_to_block,
    }


def _rpc_client(ep):
    import os

    from ..distributed.ps_rpc import PSClient, _endpoints_from_env

    # PADDLE_PSERVER_ENDPOINTS names a REPLICA group (primary +
    # backups). When this op targets the group's primary, hand the
    # client the whole list so it can fail over; any other endpoint
    # (a different shard) stays pinned.
    replicas = _endpoints_from_env()
    if replicas and replicas[0] == ep:
        ep = ",".join(replicas)
    return PSClient.for_endpoint(
        ep, trainer_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))


def deliver_grad(name, ep, val, trainer_id=None):
    """Push one gradient to a pserver endpoint — in-process emulated
    server or real socket RPC. Shared by the sync `send` op and the
    async Communicator flusher."""
    import os

    server = _EMULATED_SERVERS.get(ep)
    if server is not None:
        if server.get("mode") == "fl":
            # federated round: trainers send locally-trained PARAMS;
            # once Fanin DISTINCT trainers contributed, the server
            # installs their mean (FedAvg — the aggregation the
            # reference's FL optimize blocks express,
            # fl_listen_and_serv_op.cc:100). Keyed by trainer id: a
            # duplicate send from one trainer REPLACES its entry, it
            # must not crowd out a lagging peer
            if trainer_id is None:
                trainer_id = int(os.environ.get("PADDLE_TRAINER_ID",
                                                "0"))
            pend = server["pending"].setdefault(name, {})
            pend[trainer_id] = np.asarray(val)
            if len(pend) >= server["fanin"]:
                server["executor"]._write_var(
                    server["scope"], name,
                    np.mean(np.stack(list(pend.values())), axis=0))
                server["pending"][name] = {}
            return
        server["executor"]._write_var(server["scope"], name,
                                      np.asarray(val))
        sub = server["grad_to_block"].get(name)
        if sub is not None:
            server["executor"].run_block(sub, server["scope"])
    elif ep:
        # cross-process endpoint: real socket RPC (grpc_client.cc
        # counterpart); the server applies the round protocol
        _rpc_client(ep).send_grad(name, np.asarray(val))
    else:
        raise RuntimeError(
            "send: no server at %r — run the pserver program "
            "(listen_and_serv) first, or use the collective fleet "
            "for multi-host" % ep)


@register_host_op(
    "send",
    inputs=[In("X", duplicable=True, no_grad=True)],
    outputs=[Out("Out", duplicable=True, dispensable=True)],
    attrs={"epmap": [], "sync_mode": True, "table_name": ""},
)
def _send(executor, op, scope):
    eps = op.attrs.get("epmap", [])
    sync = bool(op.attrs.get("sync_mode", True))
    if not sync:
        from ..communicator import global_communicator

        comm = global_communicator()
        if comm is not None and comm.is_running():
            # async mode: the Communicator batches and pushes in the
            # background (communicator.h:176 AsyncCommunicator)
            for name, ep in zip(op.input("X"),
                                eps or [""] * len(op.input("X"))):
                comm.enqueue(name, ep,
                             np.asarray(executor._read_var(scope, name)))
            return
    for name, ep in zip(op.input("X"), eps or [""] * len(op.input("X"))):
        val = executor._read_var(scope, name)
        deliver_grad(name, ep, val)


@register_host_op(
    "recv",
    inputs=[In("X", duplicable=True, dispensable=True, no_grad=True)],
    outputs=[Out("Out", duplicable=True)],
    attrs={"epmap": [], "table_name": ""},
)
def _recv(executor, op, scope):
    eps = op.attrs.get("epmap", [])
    for name, ep in zip(op.output("Out"), eps or [""] * len(op.output("Out"))):
        server = _EMULATED_SERVERS.get(ep)
        if server is not None:
            val = server["executor"]._read_var(server["scope"], name)
            if val is None:
                raise RuntimeError("recv: server %r has no var %r"
                                   % (ep, name))
            executor._write_var(scope, name, np.asarray(val))
        elif ep:
            executor._write_var(scope, name, _rpc_client(ep).get_param(name))
        else:
            raise RuntimeError("recv: no server at %r" % ep)


@register_host_op(
    "send_barrier",
    inputs=[In("X", duplicable=True, dispensable=True, no_grad=True)],
    outputs=[Out("Out", duplicable=True, dispensable=True)],
    attrs={"endpoints": [], "trainer_id": 0},
)
def _send_barrier(executor, op, scope):
    # in-process emulation applies sends synchronously; RPC endpoints
    # need the real barrier to close the sync round (RunSyncLoop)
    for ep in op.attrs.get("endpoints", []):
        if ep and ep not in _EMULATED_SERVERS:
            _rpc_client(ep).send_barrier()


@register_host_op(
    "fetch_barrier",
    inputs=[In("X", duplicable=True, dispensable=True, no_grad=True)],
    outputs=[Out("Out", duplicable=True, dispensable=True)],
    attrs={"endpoints": [], "trainer_id": 0},
)
def _fetch_barrier(executor, op, scope):
    for ep in op.attrs.get("endpoints", []):
        if ep and ep not in _EMULATED_SERVERS:
            _rpc_client(ep).fetch_barrier()


# -- distributed sparse tables (pslib path) ---------------------------------
# Parity: operators/distributed_ops/distributed_lookup_table_op.cc +
# framework/fleet/fleet_wrapper.h:84 (PullSparseVarsSync /
# PushSparseVarsAsync) + downpour_worker.cc. The table lives ROW-SLICED
# across pservers (slice_variable blocks); the trainer partitions global
# ids by row range, pulls each server's rows, and pushes merged sparse
# grads back — the server applies its optimize sub-block per push.


def _table_partition(ids_flat, starts, counts):
    """Yield (ep_index, mask, local_rows) per hosting server."""
    for k, (s, c) in enumerate(zip(starts, counts)):
        mask = (ids_flat >= s) & (ids_flat < s + c)
        if mask.any():
            yield k, mask, (ids_flat[mask] - s).astype(np.int64)


def _emulated_pull(server, name, local_rows):
    tbl = server["executor"]._read_var(server["scope"], name)
    if tbl is None:
        raise RuntimeError("pull_sparse: server has no table %r" % name)
    return np.asarray(tbl)[local_rows]


def _emulated_push(server, grad_name, param_name, local_rows, values):
    from ..core.tensor import LoDTensor, SelectedRows

    tbl = server["executor"]._read_var(server["scope"], param_name)
    height = int(np.asarray(tbl).shape[0]) if tbl is not None \
        else int(local_rows.max()) + 1
    sr = SelectedRows(rows=local_rows.tolist(), height=height)
    sr._value = LoDTensor(values)
    server["executor"]._write_var(server["scope"], grad_name, sr)
    sub = server["grad_to_block"].get(grad_name)
    if sub is not None:
        server["executor"].run_block(sub, server["scope"])


@register_host_op(
    "distributed_lookup_table",
    inputs=[In("Ids", no_grad=True), In("W", dispensable=True,
                                        no_grad=True)],
    outputs=[Out("Outputs")],
    attrs={"table_name": "", "endpoints": [], "row_starts": [],
           "row_counts": [], "embed_dim": 0, "padding_idx": -1,
           "squeeze_last": True, "dtype": "float32"},
)
def _distributed_lookup_table(executor, op, scope):
    """Sparse pull: route each id to the pserver hosting its row block,
    pull value rows, reassemble [ids shape..., D]."""
    ids = np.asarray(executor._read_var(scope, op.input("Ids")[0]))
    squeeze = bool(op.attrs.get("squeeze_last", True)) \
        and ids.ndim >= 2 and ids.shape[-1] == 1
    out_shape = (tuple(ids.shape[:-1]) if squeeze else tuple(ids.shape))
    flat = ids.reshape(-1).astype(np.int64)
    d = int(op.attrs["embed_dim"])
    table = op.attrs["table_name"]
    eps = op.attrs["endpoints"]
    out = np.zeros((flat.size, d),
                   dtype=np.dtype(op.attrs.get("dtype", "float32")))
    for k, mask, local in _table_partition(
            flat, op.attrs["row_starts"], op.attrs["row_counts"]):
        ep = eps[k]
        server = _EMULATED_SERVERS.get(ep)
        if server is not None:
            rows = _emulated_pull(server, table, local)
        else:
            rows = _rpc_client(ep).pull_sparse(table, local)
        out[mask] = rows
    pad = int(op.attrs.get("padding_idx", -1))
    if pad >= 0:
        out[flat == pad] = 0.0
    executor._write_var(scope, op.output("Outputs")[0],
                        out.reshape(out_shape + (d,)))


@register_host_op(
    "distributed_push_sparse",
    inputs=[In("Ids", no_grad=True), In("OutGrad", no_grad=True)],
    outputs=[],
    attrs={"table_name": "", "grad_name": "", "endpoints": [],
           "row_starts": [], "row_counts": [], "padding_idx": -1,
           "squeeze_last": True},
)
def _distributed_push_sparse(executor, op, scope):
    """Sparse push: merge duplicate ids client-side (the reference's
    MergeAdd before push), partition by row range, push each server its
    local (rows, grads); the server applies its optimizer sub-block."""
    ids = np.asarray(executor._read_var(scope, op.input("Ids")[0]))
    og = np.asarray(executor._read_var(scope, op.input("OutGrad")[0]))
    flat = ids.reshape(-1).astype(np.int64)
    d = og.shape[-1]
    vals = np.asarray(og).reshape(-1, d)
    pad = int(op.attrs.get("padding_idx", -1))
    if pad >= 0:
        keep = flat != pad
        flat, vals = flat[keep], vals[keep]
    uniq, inv = np.unique(flat, return_inverse=True)
    merged = np.zeros((uniq.size, d), dtype=vals.dtype)
    np.add.at(merged, inv, vals)
    table = op.attrs["table_name"]
    gname = op.attrs.get("grad_name") or (table + "@GRAD")
    eps = op.attrs["endpoints"]
    for k, mask, local in _table_partition(
            uniq, op.attrs["row_starts"], op.attrs["row_counts"]):
        ep = eps[k]
        server = _EMULATED_SERVERS.get(ep)
        if server is not None:
            _emulated_push(server, gname, table, local, merged[mask])
        else:
            _rpc_client(ep).push_sparse(gname, local, merged[mask],
                                        param=table)


import weakref

# scope -> {table@epmap: count}; weak keys so a dead trainer scope's
# counters vanish with it (id()-keyed dicts alias on address reuse)
_GEO_COUNTERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


@register_host_op(
    "geo_send",
    inputs=[In("Param", no_grad=True), In("Snapshot", no_grad=True)],
    outputs=[Out("SnapshotOut")],
    attrs={"epmap": [], "table_name": "", "push_nums": 100, "trainers": 1},
)
def _geo_send(executor, op, scope):
    """Geo-SGD delta push (reference geo_sgd_transpiler + the
    GeoSgdCommunicator threads, communicator.h:383): every `push_nums`
    calls, ships (param - snapshot) to the hosting pserver (which
    applies param += delta) and refreshes the snapshot — deltas
    accumulate locally between pushes. Other calls are a counter bump."""
    table = op.attrs.get("table_name", "")
    # per-trainer cadence: counters live per calling scope, or
    # co-resident emulated trainers would share one push counter
    key = "%s@%s" % (table, ",".join(op.attrs.get("epmap", [])))
    counters = _GEO_COUNTERS.setdefault(scope, {})
    counters[key] = counters.get(key, 0) + 1
    if counters[key] % max(int(op.attrs.get("push_nums", 100)), 1):
        return
    ep = (op.attrs.get("epmap") or [""])[0]
    server = _EMULATED_SERVERS.get(ep)
    if server is None:
        raise RuntimeError(
            "geo_send: no server at %r — run the pserver program first"
            % ep)
    param = np.asarray(executor._read_var(scope, op.input("Param")[0]))
    snap = np.asarray(executor._read_var(scope, op.input("Snapshot")[0]))
    dname = "%s.geo.delta" % table
    server["executor"]._write_var(server["scope"], dname, param - snap)
    sub = server["grad_to_block"].get(dname)
    if sub is not None:
        # param += delta via the server's optimize sub-block
        server["executor"].run_block(sub, server["scope"])
    else:
        cur = np.asarray(server["executor"]._read_var(server["scope"],
                                                      table))
        server["executor"]._write_var(server["scope"], table,
                                      cur + (param - snap))
    executor._write_var(scope, op.output("SnapshotOut")[0], param)


def reset_geo_counters():
    _GEO_COUNTERS.clear()


@register_host_op(
    "ref_by_trainer_id",
    inputs=[In("X", duplicable=True, no_grad=True),
            In("TrainerId", no_grad=True)],
    outputs=[Out("Out")])
def _ref_by_trainer_id(executor, op, scope):
    """Select X[trainer_id] (reference
    distributed_ops/ref_by_trainer_id_op.h) — routes a per-trainer
    slice (e.g. a merged-ids partition) to this trainer."""
    tid = int(np.asarray(
        executor._read_var(scope, op.input("TrainerId")[0])).reshape(-1)[0])
    names = op.input("X")
    if not 0 <= tid < len(names):
        raise IndexError("trainer id %d out of range for %d inputs"
                         % (tid, len(names)))
    val = executor._read_var(scope, names[tid])
    executor._write_var(scope, op.output("Out")[0], np.asarray(val))


@register_host_op(
    "fl_listen_and_serv",
    inputs=[In("X", duplicable=True, dispensable=True, no_grad=True)],
    outputs=[],
    attrs={"endpoint": "", "optimize_blocks": [], "sync_mode": True,
           "Fanin": 1},
)
def _fl_listen_and_serv(executor, op, scope):
    """Federated-learning server round (reference
    distributed_ops/fl_listen_and_serv_op.cc): each round, trainers GET
    the global parameters, train LOCALLY, and SEND their updated
    parameters; once Fanin copies of a parameter arrive the server
    installs the FedAvg mean. Aggregation here is the built-in mean
    (deliver_grad fl mode) rather than reference-style optimize
    sub-blocks — the contract (round protocol + averaged params served
    to the next recv) is identical."""
    _EMULATED_SERVERS[op.attrs["endpoint"]] = {
        "executor": executor,
        "scope": scope,
        "grad_to_block": {},
        "mode": "fl",
        "fanin": int(op.attrs.get("Fanin", 1)),
        "pending": {},
    }
