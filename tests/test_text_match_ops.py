"""Text-matching + SSD-mining op family (registry-parity wave 5):
match_matrix_tensor, sequence_topk_avg_pooling, similarity_focus,
lookup_table_dequant, mine_hard_examples, retinanet_target_assign.
Each test reproduces the reference kernel's numeric contract with an
independent numpy oracle."""
import struct

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import framework
from paddle_tpu.core.tensor import LoDTensor


def _run_op(op_type, inputs, outputs, attrs, feeds):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        blk = main.global_block()
        for n in list(inputs.values()):
            for name in n:
                if not blk.has_var_local(name):
                    blk.create_var(name=name, shape=None,
                                   dtype="float32")
        for n in list(outputs.values()):
            for name in n:
                blk.create_var(name=name, shape=None, dtype="float32")
        op = framework.Operator(blk, op_type, inputs, outputs, attrs)
        op._id = main._next_op_id()
        blk.ops.append(op)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        for name, v in feeds.items():
            exe._core._write_var(scope, name, v)
        exe._core.run_block(main.global_block(), scope)
        out = {}
        for names in outputs.values():
            for name in names:
                var = scope.find_var(name)
                out[name] = var.raw() if var is not None else None
    return out


def test_lookup_table_dequant():
    rng = np.random.RandomState(0)
    table = rng.random_sample((17, 10)).astype("float32")
    ids = rng.randint(0, 17, (4, 1)).astype("int64")
    out = _run_op("lookup_table_dequant",
                  {"W": ["w"], "Ids": ["ids"]}, {"Out": ["o"]}, {},
                  {"w": table, "ids": ids})["o"]
    # oracle straight from the reference test's formula
    expect = []
    for i in ids.ravel():
        lo, hi = table[i][0], table[i][1]
        row = []
        for val in table[i][2:]:
            row += [b * (hi - lo) / 256.0 + lo
                    for b in bytearray(struct.pack("f", val))]
        expect.append(row)
    np.testing.assert_allclose(np.asarray(out.array),
                               np.asarray(expect, "float32"),
                               rtol=1e-5, atol=1e-6)


def test_match_matrix_tensor_matches_oracle():
    rng = np.random.RandomState(1)
    x_lod, y_lod = [0, 1, 3, 5], [0, 3, 4, 8]
    h, dim_t = 6, 3
    x = rng.random_sample((5, h)).astype("float32")
    y = rng.random_sample((8, h)).astype("float32")
    w = rng.random_sample((h, dim_t, h)).astype("float32")
    xt = LoDTensor(x)
    xt.set_lod([x_lod])
    yt = LoDTensor(y)
    yt.set_lod([y_lod])
    out = _run_op("match_matrix_tensor",
                  {"X": ["x"], "Y": ["y"], "W": ["w"]},
                  {"Out": ["o"], "Tmp": ["tmp"]}, {"dim_t": dim_t},
                  {"x": xt, "y": yt, "w": w})
    # oracle: independently computed bilinear grids
    w_t = w.transpose(1, 0, 2)
    expect, lod = [], [0]
    for i in range(3):
        xs = x[x_lod[i]:x_lod[i + 1]]
        ys = y[y_lod[i]:y_lod[i + 1]]
        grid = np.einsum("ih,thk,jk->tij", xs, w_t, ys)
        expect.append(grid.reshape(-1, 1))
        lod.append(lod[-1] + grid.size)
    np.testing.assert_allclose(np.asarray(out["o"].array),
                               np.concatenate(expect), rtol=1e-5,
                               atol=1e-5)
    assert out["o"].lod() == [lod]


def test_match_matrix_tensor_trains():
    """End-to-end: grads flow into X, Y, and W (reference check_grad)."""
    rng = np.random.RandomState(2)
    h, dim_t = 4, 2
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[-1, h], dtype="float32",
                       lod_level=1)
        y = fluid.data(name="y", shape=[-1, h], dtype="float32",
                       lod_level=1)
        w = fluid.layers.create_parameter([h, dim_t, h], "float32",
                                          name="w_mm")
        blk = main.global_block()
        o = blk.create_var(name="mm_out", shape=[-1, 1], dtype="float32")
        blk.create_var(name="mm_tmp", shape=None, dtype="float32")
        op = framework.Operator(
            blk, "match_matrix_tensor",
            {"X": ["x"], "Y": ["y"], "W": ["w_mm"]},
            {"Out": ["mm_out"], "Tmp": ["mm_tmp"]}, {"dim_t": dim_t})
        op._id = main._next_op_id()
        blk.ops.append(op)
        o.stop_gradient = False
        loss = fluid.layers.reduce_mean(o)
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    xt = LoDTensor(rng.random_sample((4, h)).astype("float32"))
    xt.set_lod([[0, 2, 4]])
    yt = LoDTensor(rng.random_sample((5, h)).astype("float32"))
    yt.set_lod([[0, 3, 5]])
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        w0 = np.asarray(scope.find_var("w_mm").raw().array).copy()
        (l1,) = exe.run(main, feed={"x": xt, "y": yt},
                        fetch_list=[loss])
        w1 = np.asarray(scope.find_var("w_mm").raw().array)
    assert np.isfinite(float(np.ravel(l1)[0]))
    assert np.abs(w1 - w0).max() > 1e-8  # W actually updated


def test_sequence_topk_avg_pooling():
    """One pair, 2 channels, 2x3 grid, topks [1, 2]."""
    chan, rs, cs = 2, 2, 3
    grid = np.asarray(
        [[[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]],     # channel 0
         [[9.0, 7.0, 8.0], [6.0, 6.5, 6.25]]],   # channel 1
        "float32")
    xt = LoDTensor(grid.reshape(-1, 1))
    xt.set_lod([[0, chan * rs * cs]])
    rowt = LoDTensor(np.zeros((rs, 1), "float32"))
    rowt.set_lod([[0, rs]])
    colt = LoDTensor(np.zeros((cs, 1), "float32"))
    colt.set_lod([[0, cs]])
    out = _run_op("sequence_topk_avg_pooling",
                  {"X": ["x"], "ROW": ["r"], "COLUMN": ["c"]},
                  {"Out": ["o"], "pos": ["p"]},
                  {"topks": [1, 2], "channel_num": chan},
                  {"x": xt, "r": rowt, "c": colt})["o"]
    got = np.asarray(out.array)
    # rows x (chan * k_num); per row/channel: [top1, mean(top2)]
    expect = np.asarray([
        [3.0, 2.5, 9.0, 8.5],
        [5.0, 4.5, 6.5, 6.375],
    ], "float32")
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_similarity_focus():
    rng = np.random.RandomState(3)
    x = rng.random_sample((2, 3, 2, 2)).astype("float32")
    out = np.asarray(_run_op(
        "similarity_focus", {"X": ["x"]}, {"Out": ["o"]},
        {"axis": 1, "indexes": [0]}, {"x": x})["o"].array)
    # oracle: greedy row/col tagging on slice [b, 0]
    expect = np.zeros_like(x)
    for b in range(2):
        sl = x[b, 0]
        order = np.argsort(-sl.ravel(), kind="stable")
        t1 = np.zeros(2, bool)
        t2 = np.zeros(2, bool)
        for f in order:
            i1, i2 = divmod(int(f), 2)
            if t1[i1] or t2[i2]:
                continue
            t1[i1] = t2[i2] = True
            expect[b, :, i1, i2] = 1
    np.testing.assert_array_equal(out, expect)


def test_mine_hard_examples_max_negative():
    cls = np.asarray([[0.1, 0.9, 0.3, 0.7]], "float32")
    mi = np.asarray([[0, -1, -1, -1]], "int32")
    md = np.asarray([[0.9, 0.1, 0.2, 0.3]], "float32")
    out = _run_op("mine_hard_examples",
                  {"ClsLoss": ["c"], "MatchIndices": ["m"],
                   "MatchDist": ["d"]},
                  {"NegIndices": ["n"], "UpdatedMatchIndices": ["u"]},
                  {"neg_pos_ratio": 2.0, "neg_dist_threshold": 0.5,
                   "mining_type": "max_negative"},
                  {"c": cls, "m": mi, "d": md})
    # 1 positive -> up to 2 negatives; eligible = {1,2,3}; hardest by
    # cls loss: 1 (0.9) and 3 (0.7)
    np.testing.assert_array_equal(
        np.asarray(out["n"].array).ravel(), [1, 3])
    np.testing.assert_array_equal(np.asarray(out["u"].array), mi)


def test_retinanet_target_assign():
    anchors = np.asarray([[0, 0, 9, 9], [10, 10, 19, 19],
                          [50, 50, 59, 59]], "float32")
    gt = LoDTensor(np.asarray([[0, 0, 9, 9]], "float32"))
    gt.set_lod([[0, 1]])
    lbl = LoDTensor(np.asarray([[3]], "int32"))
    lbl.set_lod([[0, 1]])
    crowd = LoDTensor(np.zeros((1, 1), "int32"))
    crowd.set_lod([[0, 1]])
    im = np.asarray([[60, 60, 1.0]], "float32")
    out = _run_op(
        "retinanet_target_assign",
        {"Anchor": ["a"], "GtBoxes": ["g"], "GtLabels": ["l"],
         "IsCrowd": ["ic"], "ImInfo": ["im"]},
        {"LocationIndex": ["li"], "ScoreIndex": ["si"],
         "TargetBBox": ["tb"], "TargetLabel": ["tl"],
         "BBoxInsideWeight": ["bw"], "ForegroundNumber": ["fn"]},
        {"positive_overlap": 0.5, "negative_overlap": 0.4},
        {"a": anchors, "g": gt, "l": lbl, "ic": crowd, "im": im})
    # anchor 0 is fg (iou 1.0, label 3); anchors 1,2 bg (label 0); ALL
    # anchors scored (no subsampling)
    np.testing.assert_array_equal(
        np.asarray(out["li"].array).ravel(), [0])
    assert sorted(np.asarray(out["si"].array).ravel().tolist()) == \
        [0, 1, 2]
    labels = np.asarray(out["tl"].array).ravel()
    assert labels[0] == 3 and set(labels[1:]) == {0}
    np.testing.assert_array_equal(
        np.asarray(out["fn"].array).ravel(), [2])  # fg + 1


def test_generate_proposal_labels():
    rois = LoDTensor(np.asarray(
        [[0, 0, 9, 9], [0, 0, 4, 4], [30, 30, 39, 39]], "float32"))
    rois.set_lod([[0, 3]])
    gts = LoDTensor(np.asarray([[0, 0, 9, 9]], "float32"))
    gts.set_lod([[0, 1]])
    gtc = LoDTensor(np.asarray([[2]], "int32"))
    gtc.set_lod([[0, 1]])
    crowd = LoDTensor(np.zeros((1, 1), "int32"))
    crowd.set_lod([[0, 1]])
    im = np.asarray([[60, 60, 1.0]], "float32")
    out = _run_op(
        "generate_proposal_labels",
        {"RpnRois": ["r"], "GtClasses": ["gc"], "IsCrowd": ["ic"],
         "GtBoxes": ["gb"], "ImInfo": ["im"]},
        {"Rois": ["ro"], "LabelsInt32": ["lb"], "BboxTargets": ["bt"],
         "BboxInsideWeights": ["iw"], "BboxOutsideWeights": ["ow"]},
        {"batch_size_per_im": 8, "fg_fraction": 0.5, "fg_thresh": 0.5,
         "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
         "bbox_reg_weights": [1.0, 1.0, 1.0, 1.0], "class_nums": 4,
         "use_random": False, "seed": 0},
        {"r": rois, "gc": gtc, "ic": crowd, "gb": gts, "im": im})
    labels = np.asarray(out["lb"].array).ravel()
    ro = np.asarray(out["ro"].array)
    # fg: the gt itself (iou 1) + roi[0] (identical box); bg: the rest
    assert (labels == 2).sum() == 2, labels
    assert (labels == 0).sum() == len(labels) - 2
    bt = np.asarray(out["bt"].array)
    iw = np.asarray(out["iw"].array)
    # fg rows carry class-2 slots; identical boxes -> zero deltas
    for k, lab in enumerate(labels):
        if lab == 2:
            np.testing.assert_allclose(bt[k, 8:12], 0.0, atol=1e-6)
            np.testing.assert_array_equal(iw[k, 8:12], 1.0)
        assert iw[k, :8].sum() == 0 and iw[k, 12:].sum() == 0


def test_deformable_psroi_pooling_numeric_grad():
    """Forward sanity (zero offsets + aligned roi averages the bin) and
    numeric-vs-analytic grads for Input and Trans (the reference
    check_grad contract)."""
    rng = np.random.RandomState(4)
    x = rng.random_sample((1, 4, 8, 8)).astype("float64")
    rois = LoDTensor(np.asarray([[0, 0, 7, 7]], "float64"))
    rois.set_lod([[0, 1]])
    trans = (rng.random_sample((1, 2, 2, 2)) * 0.2).astype("float64")
    attrs = {"no_trans": False, "spatial_scale": 1.0, "output_dim": 1,
             "group_size": [2, 2], "pooled_height": 2,
             "pooled_width": 2, "part_size": [2, 2],
             "sample_per_part": 2, "trans_std": 0.1}

    def forward(xv, tv):
        out = _run_op(
            "deformable_psroi_pooling",
            {"Input": ["xi"], "ROIs": ["ri"], "Trans": ["ti"]},
            {"Output": ["oo"], "TopCount": ["tc"]}, attrs,
            {"xi": xv, "ri": rois, "ti": tv})
        return np.asarray(out["oo"].array), out["tc"]

    y0, tc = forward(x, trans)
    assert np.isfinite(y0).all() and y0.shape == (1, 1, 2, 2)

    # analytic grads via the grad op with a ones cotangent
    og = np.ones_like(y0)
    gout = _run_op(
        "deformable_psroi_pooling_grad",
        {"Input": ["xi"], "ROIs": ["ri"], "Trans": ["ti"],
         "TopCount": ["tc"], "Output@GRAD": ["og"]},
        {"Input@GRAD": ["gx"], "Trans@GRAD": ["gt"]}, attrs,
        {"xi": x, "ri": rois, "ti": trans, "tc": tc, "og": og})
    gx = np.asarray(gout["gx"].array)
    gt = np.asarray(gout["gt"].array)

    eps = 1e-3
    for idx in [(0, 0, 2, 3), (0, 1, 5, 5), (0, 3, 7, 0)]:
        xp = x.copy()
        xp[idx] += eps
        xm = x.copy()
        xm[idx] -= eps
        num = (forward(xp, trans)[0].sum()
               - forward(xm, trans)[0].sum()) / (2 * eps)
        np.testing.assert_allclose(gx[idx], num, rtol=2e-3, atol=1e-5)
    for idx in [(0, 0, 0, 1), (0, 1, 1, 0)]:
        tp = trans.copy()
        tp[idx] += eps
        tm = trans.copy()
        tm[idx] -= eps
        num = (forward(x, tp)[0].sum()
               - forward(x, tm)[0].sum()) / (2 * eps)
        np.testing.assert_allclose(gt[idx], num, rtol=5e-3, atol=1e-5)


def test_roi_perspective_transform_axis_aligned():
    """An axis-aligned rectangular quad degenerates to a plain crop:
    output equals bilinear samples on the grid, mask all ones, and the
    grad scatters exactly through the sampled corners."""
    rng = np.random.RandomState(5)
    x = rng.random_sample((1, 2, 8, 8)).astype("float32")
    # quad (1,1) -> (6,1) -> (6,6) -> (1,6); transformed 6x6
    quad = np.asarray([[1, 1, 6, 1, 6, 6, 1, 6]], "float32")
    rois = LoDTensor(quad)
    rois.set_lod([[0, 1]])
    attrs = {"transformed_height": 6, "transformed_width": 6,
             "spatial_scale": 1.0}
    out = _run_op(
        "roi_perspective_transform",
        {"X": ["x"], "ROIs": ["r"]},
        {"Out": ["o"], "Mask": ["m"], "TransformMatrix": ["tm"],
         "Out2InIdx": [], "Out2InWeights": []}, attrs,
        {"x": x, "r": rois})
    o = np.asarray(out["o"].array)
    mask = np.asarray(out["m"].array)
    assert o.shape == (1, 2, 6, 6)
    assert mask.min() == 1  # fully inside the quad and the image
    # identity-scaled crop: out[h, w] == x[1+h, 1+w]
    np.testing.assert_allclose(o[0, :, :, :], x[0, :, 1:7, 1:7],
                               rtol=1e-5, atol=1e-5)
    # grad: ones cotangent scatters exactly once per sampled pixel
    gout = _run_op(
        "roi_perspective_transform_grad",
        {"X": ["x"], "ROIs": ["r"], "Mask": ["m"], "Out@GRAD": ["og"]},
        {"X@GRAD": ["gx"]}, attrs,
        {"x": x, "r": rois, "m": out["m"], "og": np.ones_like(o)})
    gx = np.asarray(gout["gx"].array)
    np.testing.assert_allclose(gx[0, 0, 1:7, 1:7], 1.0, atol=1e-6)
    assert gx[0, 0, 0, :].sum() == 0


def test_generate_mask_labels_rect_poly():
    """A rectangular polygon rasterizes exactly; the mask target lands
    in the fg roi's class slot with -1 elsewhere."""
    res, ncls = 4, 3
    im = np.asarray([[16, 16, 1.0]], "float32")
    gtc = LoDTensor(np.asarray([[1]], "int32"))
    gtc.set_lod([[0, 1]])
    crowd = LoDTensor(np.zeros((1, 1), "int32"))
    crowd.set_lod([[0, 1]])
    # polygon: rectangle [2,2]-[10,10] (one gt, one polygon, 4 points)
    pts = np.asarray([[2, 2], [10, 2], [10, 10], [2, 10]], "float32")
    segs = LoDTensor(pts)
    segs.set_lod([[0, 1], [0, 4]])
    # two rois: one fg matching the rect's left half, one bg
    rois = LoDTensor(np.asarray([[2, 2, 6, 10], [12, 12, 15, 15]],
                                "float32"))
    rois.set_lod([[0, 2]])
    labels = LoDTensor(np.asarray([[2], [0]], "int32"))
    labels.set_lod([[0, 2]])
    out = _run_op(
        "generate_mask_labels",
        {"ImInfo": ["im"], "GtClasses": ["gc"], "IsCrowd": ["ic"],
         "GtSegms": ["gs"], "Rois": ["ro"], "LabelsInt32": ["lb"]},
        {"MaskRois": ["mr"], "RoiHasMaskInt32": ["hm"],
         "MaskInt32": ["mi"]},
        {"num_classes": ncls, "resolution": res},
        {"im": im, "gc": gtc, "ic": crowd, "gs": segs, "ro": rois,
         "lb": labels})
    mr = np.asarray(out["mr"].array)
    hm = np.asarray(out["hm"].array).ravel()
    mi = np.asarray(out["mi"].array)
    assert mr.shape == (1, 4)  # one fg roi
    np.testing.assert_array_equal(hm, [0])
    assert mi.shape == (1, ncls * res * res)
    # the roi sits fully inside the polygon -> class-2 slot all ones
    cls2 = mi[0, 2 * res * res:3 * res * res]
    np.testing.assert_array_equal(cls2, 1)
    assert (mi[0, :2 * res * res] == -1).all()
