"""Optimizer update ops.

Parity: /root/reference/paddle/fluid/operators/optimizers/ (sgd, momentum,
lars_momentum, adam, adamax, adagrad, decayed_adagrad, adadelta, rmsprop,
ftrl, lamb, dpsgd). Contract kept from the reference: Param/Moment inputs
are re-bound through same-named *Out outputs (is_ref), so the executor's
rebinding (and buffer donation in compiled mode) realises in-place update.
All are grad=None (never differentiated).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import In, Out, register_op


def _op(name, inputs, outputs, attrs, fn):
    register_op(
        name,
        inputs=[In(i) if isinstance(i, str) else i for i in inputs],
        outputs=[Out(o, is_ref=True) for o in outputs],
        attrs=attrs,
        grad=None,
    )(fn)


def _lr(ins):
    return ins["LearningRate"].reshape(())


def _maybe_densify_grad(ins):
    """SelectedRows grad (sparse embedding path) → dense, for optimizers
    whose reference kernels have no row-wise sparse variant. Exact
    non-lazy semantics: densify accumulates duplicate rows."""
    from ..core.tensor import SelectedRows

    g = ins["Grad"]
    if isinstance(g, SelectedRows):
        ins = dict(ins)
        ins["Grad"] = g.to_dense()
    return ins


def _sgd(ins, attrs):
    from ..core.tensor import SelectedRows

    g = ins["Grad"]
    if isinstance(g, SelectedRows):
        # reference sgd_op.h SelectedRows kernel: update only the
        # touched rows (duplicates accumulate via scatter-add)
        rows = jnp.asarray(g.rows(), dtype=jnp.int32)
        vals = g.get_tensor().array
        p = ins["Param"].at[rows].add(-_lr(ins) * vals)
        return {"ParamOut": p}
    return {"ParamOut": ins["Param"] - _lr(ins) * g}


_op("sgd", ["Param", "Grad", "LearningRate"], ["ParamOut"], {}, _sgd)


def _momentum(ins, attrs):
    ins = _maybe_densify_grad(ins)
    mu = attrs.get("mu", 0.9)
    v = mu * ins["Velocity"] + ins["Grad"]
    if attrs.get("use_nesterov", False):
        p = ins["Param"] - (ins["Grad"] + mu * v) * _lr(ins)
    else:
        p = ins["Param"] - _lr(ins) * v
    return {"ParamOut": p, "VelocityOut": v}


_op(
    "momentum",
    ["Param", "Grad", "Velocity", "LearningRate"],
    ["ParamOut", "VelocityOut"],
    {"mu": 0.9, "use_nesterov": False, "regularization_method": "",
     "regularization_coeff": 0.0},
    _momentum,
)


def _lars_momentum(ins, attrs):
    mu = attrs.get("mu", 0.9)
    lars_coeff = attrs.get("lars_coeff", 0.001)
    wd = attrs.get("lars_weight_decay", 0.0005)
    eps = attrs.get("epsilon", 0.0)
    p, g, v = ins["Param"], ins["Grad"], ins["Velocity"]
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lars_coeff * p_norm / (g_norm + wd * p_norm + eps),
        jnp.ones_like(p_norm),
    )
    v_out = mu * v + _lr(ins) * local_lr * (g + wd * p)
    return {"ParamOut": p - v_out, "VelocityOut": v_out}


_op(
    "lars_momentum",
    ["Param", "Grad", "Velocity", "LearningRate"],
    ["ParamOut", "VelocityOut"],
    {"mu": 0.9, "lars_coeff": 0.001, "lars_weight_decay": 0.0005, "epsilon": 0.0},
    _lars_momentum,
)


def _adam(ins, attrs):
    ins = _maybe_densify_grad(ins)
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    p, g = ins["Param"], ins["Grad"]
    m1 = b1 * ins["Moment1"] + (1 - b1) * g
    m2 = b2 * ins["Moment2"] + (1 - b2) * jnp.square(g)
    b1pow, b2pow = ins["Beta1Pow"].reshape(()), ins["Beta2Pow"].reshape(())
    lr = _lr(ins) * jnp.sqrt(1 - b2pow) / (1 - b1pow)
    p_out = p - lr * m1 / (jnp.sqrt(m2) + eps)
    return {
        "ParamOut": p_out,
        "Moment1Out": m1,
        "Moment2Out": m2,
        "Beta1PowOut": (b1pow * b1).reshape(ins["Beta1Pow"].shape),
        "Beta2PowOut": (b2pow * b2).reshape(ins["Beta2Pow"].shape),
    }


_op(
    "adam",
    ["Param", "Grad", "LearningRate", "Moment1", "Moment2", "Beta1Pow", "Beta2Pow"],
    ["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut", "Beta2PowOut"],
    {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8, "lazy_mode": False,
     "min_row_size_to_use_multithread": 1000},
    _adam,
)


def _adamw(ins, attrs):
    # AdamW decoupled weight decay (not in the v1.7 op set; provided for the
    # 2.0-alpha paddle.optimizer surface and BERT configs).
    out = _adam(ins, attrs)
    wd = attrs.get("weight_decay", 0.01)
    lr = _lr(ins)
    out["ParamOut"] = out["ParamOut"] - lr * wd * ins["Param"]
    return out


_op(
    "adamw",
    ["Param", "Grad", "LearningRate", "Moment1", "Moment2", "Beta1Pow", "Beta2Pow"],
    ["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut", "Beta2PowOut"],
    {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8, "weight_decay": 0.01},
    _adamw,
)


def _adamax(ins, attrs):
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    g = ins["Grad"]
    m = b1 * ins["Moment"] + (1 - b1) * g
    inf_norm = jnp.maximum(b2 * ins["InfNorm"], jnp.abs(g))
    b1pow = ins["Beta1Pow"].reshape(())
    lr = _lr(ins) / (1 - b1pow)
    p_out = ins["Param"] - lr * m / (inf_norm + eps)
    return {"ParamOut": p_out, "MomentOut": m, "InfNormOut": inf_norm}


_op(
    "adamax",
    ["Param", "Grad", "LearningRate", "Moment", "InfNorm", "Beta1Pow"],
    ["ParamOut", "MomentOut", "InfNormOut"],
    {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
    _adamax,
)


def _adagrad(ins, attrs):
    ins = _maybe_densify_grad(ins)
    eps = attrs.get("epsilon", 1e-6)
    g = ins["Grad"]
    moment = ins["Moment"] + jnp.square(g)
    p_out = ins["Param"] - _lr(ins) * g / (jnp.sqrt(moment) + eps)
    return {"ParamOut": p_out, "MomentOut": moment}


_op(
    "adagrad",
    ["Param", "Grad", "Moment", "LearningRate"],
    ["ParamOut", "MomentOut"],
    {"epsilon": 1e-6},
    _adagrad,
)


def _decayed_adagrad(ins, attrs):
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g = ins["Grad"]
    moment = decay * ins["Moment"] + (1 - decay) * jnp.square(g)
    p_out = ins["Param"] - _lr(ins) * g / (jnp.sqrt(moment) + eps)
    return {"ParamOut": p_out, "MomentOut": moment}


_op(
    "decayed_adagrad",
    ["Param", "Grad", "Moment", "LearningRate"],
    ["ParamOut", "MomentOut"],
    {"decay": 0.95, "epsilon": 1e-6},
    _decayed_adagrad,
)


def _adadelta(ins, attrs):
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g = ins["Grad"]
    avg_sq = rho * ins["AvgSquaredGrad"] + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((ins["AvgSquaredUpdate"] + eps) / (avg_sq + eps)) * g
    avg_upd = rho * ins["AvgSquaredUpdate"] + (1 - rho) * jnp.square(update)
    return {
        "ParamOut": ins["Param"] + update,
        "AvgSquaredGradOut": avg_sq,
        "AvgSquaredUpdateOut": avg_upd,
    }


_op(
    "adadelta",
    ["Param", "Grad", "AvgSquaredGrad", "AvgSquaredUpdate"],
    ["ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"],
    {"rho": 0.95, "epsilon": 1e-6},
    _adadelta,
)


def _rmsprop(ins, attrs):
    ins = _maybe_densify_grad(ins)
    eps = attrs.get("epsilon", 1e-10)
    decay = attrs.get("decay", 0.9)
    momentum = attrs.get("momentum", 0.0)
    centered = attrs.get("centered", False)
    g = ins["Grad"]
    ms = decay * ins["MeanSquare"] + (1 - decay) * jnp.square(g)
    if centered:
        mg = decay * ins["MeanGrad"] + (1 - decay) * g
        denom = ms - jnp.square(mg) + eps
    else:
        mg = ins["MeanGrad"]
        denom = ms + eps
    mom = momentum * ins["Moment"] + _lr(ins) * g * jax.lax.rsqrt(denom)
    return {
        "ParamOut": ins["Param"] - mom,
        "MomentOut": mom,
        "MeanSquareOut": ms,
        "MeanGradOut": mg,
    }


_op(
    "rmsprop",
    ["Param", "Grad", "LearningRate", "Moment", "MeanSquare", "MeanGrad"],
    ["ParamOut", "MomentOut", "MeanSquareOut", "MeanGradOut"],
    {"epsilon": 1e-10, "decay": 0.9, "momentum": 0.0, "centered": False},
    _rmsprop,
)


def _ftrl(ins, attrs):
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    g = ins["Grad"]
    lr = _lr(ins)
    sq_accum = ins["SquaredAccumulator"]
    lin_accum = ins["LinearAccumulator"]
    new_accum = sq_accum + jnp.square(g)
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_accum) - jnp.sqrt(sq_accum)) / lr
    else:
        sigma = (jnp.power(new_accum, -lr_power) - jnp.power(sq_accum, -lr_power)) / lr
    lin_out = lin_accum + g - sigma * ins["Param"]
    # reference ftrl_op.h shrink denominator uses 2*l2: y = sqrt/lr + 2*l2
    if lr_power == -0.5:
        x = 2.0 * l2 + jnp.sqrt(new_accum) / lr
    else:
        x = 2.0 * l2 + jnp.power(new_accum, -lr_power) / lr
    pre = jnp.clip(lin_out, -l1, l1) - lin_out
    p_out = jnp.where(jnp.abs(lin_out) > l1, pre / x, jnp.zeros_like(pre))
    return {
        "ParamOut": p_out,
        "SquaredAccumOut": new_accum,
        "LinearAccumOut": lin_out,
    }


_op(
    "ftrl",
    ["Param", "SquaredAccumulator", "LinearAccumulator", "Grad", "LearningRate"],
    ["ParamOut", "SquaredAccumOut", "LinearAccumOut"],
    {"l1": 0.0, "l2": 0.0, "lr_power": -0.5},
    _ftrl,
)


def _lamb(ins, attrs):
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    p, g = ins["Param"], ins["Grad"]
    m1 = b1 * ins["Moment1"] + (1 - b1) * g
    m2 = b2 * ins["Moment2"] + (1 - b2) * jnp.square(g)
    b1pow, b2pow = ins["Beta1Pow"].reshape(()), ins["Beta2Pow"].reshape(())
    m1_hat = m1 / (1 - b1pow)
    m2_hat = m2 / (1 - b2pow)
    r = m1_hat / (jnp.sqrt(m2_hat) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    ratio = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    return {
        "ParamOut": p - _lr(ins) * ratio * r,
        "Moment1Out": m1,
        "Moment2Out": m2,
        "Beta1PowOut": (b1pow * b1).reshape(ins["Beta1Pow"].shape),
        "Beta2PowOut": (b2pow * b2).reshape(ins["Beta2Pow"].shape),
    }


_op(
    "lamb",
    ["Param", "Grad", "LearningRate", "Moment1", "Moment2", "Beta1Pow", "Beta2Pow"],
    ["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut", "Beta2PowOut"],
    {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-6, "weight_decay": 0.01},
    _lamb,
)


@register_op(
    "fused_optimizer",
    inputs=[In("Param", duplicable=True), In("Grad", duplicable=True),
            In("LearningRate"),
            In("StateA", duplicable=True, dispensable=True),
            In("StateB", duplicable=True, dispensable=True),
            In("Beta1Pow", duplicable=True, dispensable=True),
            In("Beta2Pow", duplicable=True, dispensable=True)],
    outputs=[Out("ParamOut", duplicable=True, is_ref=True),
             Out("StateAOut", duplicable=True, is_ref=True,
                 dispensable=True),
             Out("StateBOut", duplicable=True, is_ref=True,
                 dispensable=True),
             Out("Beta1PowOut", duplicable=True, is_ref=True,
                 dispensable=True),
             Out("Beta2PowOut", duplicable=True, is_ref=True,
                 dispensable=True)],
    attrs={"op_type": "sgd", "layout": "chain", "padded_size": 0,
           "use_pallas": True},
    grad=None,
)
def _fused_optimizer(ins, attrs):
    """Single-chip fused optimizer update (core/fusion.py rewrite): ONE
    traced op replaces an optimizer instance's whole per-param update
    chain, in one of two layouts:

    - ``layout="chain"`` (default off-TPU): StateA/StateB carry the
      ORIGINAL per-param accumulators and the shared update math
      (ops/pallas/fused_optimizer._update_math — expression-identical
      to the per-param kernels) is applied pair by pair. Zero data
      movement beyond the updates themselves — on backends where XLA
      already fuses the elementwise chain, re-laying the state out
      flat was MEASURED to cost ~40% step time in per-step concats,
      so the chain layout keeps the op-count win without it.
    - ``layout="flat"`` (the TPU/pallas layout): StateA/StateB are the
      single flat re-laid-out state vars (the cross-replica sharded
      update's mechanism, minus the mesh); params/grads flatten +
      zero-pad to ``padded_size`` and ONE pallas streaming kernel
      (ops/pallas/fused_optimizer.py) read-modify-writes the whole
      buffer; updated params slice back out.

    Elementwise math per element is identical either way, so both
    layouts are bit-for-bit with the per-param chain (modulo the
    cross-program FMA-contraction bound tools/sc_smoke.py documents).
    """
    import numpy as _np

    from .pallas.fused_optimizer import (_update_math,
                                         fused_optimizer_update)

    op_type = attrs["op_type"]
    params, grads = ins["Param"], ins["Grad"]
    lr = ins["LearningRate"].reshape(())
    b1pow = ins["Beta1Pow"][0] if ins.get("Beta1Pow") else None
    b2pow = ins["Beta2Pow"][0] if ins.get("Beta2Pow") else None

    result = {}
    if attrs.get("layout", "chain") == "flat":
        sizes = [int(p.size) for p in params]
        total = sum(sizes)
        padded = int(attrs.get("padded_size") or total)

        def _flat_pad(xs):
            flat = xs[0].reshape(-1) if len(xs) == 1 else \
                jnp.concatenate([x.reshape(-1) for x in xs])
            if padded > flat.size:
                flat = jnp.concatenate(
                    [flat,
                     jnp.zeros((padded - flat.size,), flat.dtype)])
            return flat

        sa = ins["StateA"][0] if ins.get("StateA") else None
        sb = ins["StateB"][0] if ins.get("StateB") else None
        p_new, sa_out, sb_out = fused_optimizer_update(
            op_type, attrs, _flat_pad(params), _flat_pad(grads), lr,
            sa, sb,
            b1pow.reshape(()) if b1pow is not None else None,
            b2pow.reshape(()) if b2pow is not None else None,
            force_pallas=(None if attrs.get("use_pallas", True)
                          else False))
        result["ParamOut"] = []
        off = 0
        for p, k in zip(params, sizes):
            result["ParamOut"].append(
                p_new[off:off + k].reshape(p.shape))
            off += k
        result["StateAOut"] = [sa_out] if sa_out is not None else None
        result["StateBOut"] = [sb_out] if sb_out is not None else None
    else:
        sas = ins.get("StateA") or [None] * len(params)
        sbs = ins.get("StateB") or [None] * len(params)
        b1s = b1pow.reshape(()) if b1pow is not None else None
        b2s = b2pow.reshape(()) if b2pow is not None else None
        p_outs, sa_outs, sb_outs = [], [], []
        for p, g, sa, sb in zip(params, grads, sas, sbs):
            po, sao, sbo = _update_math(op_type, attrs, p,
                                        g.astype(p.dtype), lr, sa, sb,
                                        b1s, b2s)
            p_outs.append(po)
            sa_outs.append(sao)
            sb_outs.append(sbo)
        result["ParamOut"] = p_outs
        result["StateAOut"] = sa_outs if sa_outs[0] is not None \
            else None
        result["StateBOut"] = sb_outs if sb_outs[0] is not None \
            else None

    if ins.get("Beta1Pow"):
        b1 = attrs.get("beta1", 0.9)
        result["Beta1PowOut"] = [
            (b.reshape(()) * b1).reshape(_np.shape(b))
            for b in ins["Beta1Pow"]]
    if ins.get("Beta2Pow"):
        b2 = attrs.get("beta2", 0.999)
        result["Beta2PowOut"] = [
            (b.reshape(()) * b2).reshape(_np.shape(b))
            for b in ins["Beta2Pow"]]
    return result


def _dpsgd(ins, attrs):
    # Differentially-private SGD (operators/optimizers/dpsgd_op.cc):
    # clip-by-norm then noised update. Noise omitted in deterministic mode.
    clip = attrs.get("clip", 10.0)
    g = ins["Grad"]
    norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
    return {"ParamOut": ins["Param"] - _lr(ins) * g * scale}


_op(
    "dpsgd",
    ["Param", "Grad", "LearningRate"],
    ["ParamOut"],
    {"clip": 10.0, "batch_size": 16.0, "sigma": 1.0, "seed": 0},
    _dpsgd,
)


def _proximal_gd(ins, attrs):
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr = _lr(ins)
    prox = ins["Param"] - lr * ins["Grad"]
    p_out = (
        jnp.sign(prox)
        * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
        / (1.0 + lr * l2)
    )
    return {"ParamOut": p_out}


_op(
    "proximal_gd",
    ["Param", "Grad", "LearningRate"],
    ["ParamOut"],
    {"l1": 0.0, "l2": 0.0},
    _proximal_gd,
)


def _proximal_adagrad(ins, attrs):
    """Adagrad moment + proximal soft-threshold step (reference
    optimizers/proximal_adagrad_op.h)."""
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr = _lr(ins)
    m_out = ins["Moment"] + jnp.square(ins["Grad"])
    prox = ins["Param"] - lr * ins["Grad"] / jnp.sqrt(m_out)
    if l1 > 0.0:
        p_out = (jnp.sign(prox)
                 * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
                 / (1.0 + lr * l2))
    else:
        p_out = prox / (1.0 + lr * l2)
    return {"ParamOut": p_out, "MomentOut": m_out}


_op(
    "proximal_adagrad",
    ["Param", "Moment", "Grad", "LearningRate"],
    ["ParamOut", "MomentOut"],
    {"l1": 0.0, "l2": 0.0},
    _proximal_adagrad,
)


def _dgc_momentum(ins, attrs):
    """Momentum before the DGC rampup step, plain SGD after — with the
    1/nranks grad rescale dgc_op pre-multiplied (reference
    optimizers/dgc_momentum_op.h)."""
    rampup = float(attrs.get("rampup_begin_step", 0.0))
    if rampup < 0:
        # reference dgc_momentum_op.h:34: negative rampup disables the
        # whole update (early return, outputs untouched)
        return {"ParamOut": ins["Param"], "VelocityOut": ins["Velocity"],
                "Grad_out": ins["Grad"]}
    mu = attrs.get("mu", 0.9)
    nranks = ins["nranks"].reshape(()).astype(jnp.float32)
    g = ins["Grad"] / nranks
    step = ins["current_step"].reshape(()).astype(jnp.float32)
    before_rampup = step < rampup
    v = mu * ins["Velocity"] + g
    if attrs.get("use_nesterov", False):
        p_momentum = ins["Param"] - (g + mu * v) * _lr(ins)
    else:
        p_momentum = ins["Param"] - _lr(ins) * v
    p_sgd = ins["Param"] - _lr(ins) * g
    p_out = jnp.where(before_rampup, p_momentum, p_sgd)
    v_out = jnp.where(before_rampup, v, ins["Velocity"])
    return {"ParamOut": p_out, "VelocityOut": v_out, "Grad_out": g}


_op(
    "dgc_momentum",
    ["Param", "Grad", "Velocity", "LearningRate", "current_step",
     "nranks"],
    ["ParamOut", "VelocityOut", "Grad_out"],
    {"mu": 0.9, "use_nesterov": False, "rampup_begin_step": 0.0},
    _dgc_momentum,
)


def _dgc_clip_by_norm(ins, attrs):
    """clip_by_norm gated on the DGC rampup step (reference
    dgc_clip_by_norm_op.h: a no-op until current_step reaches
    rampup_begin_step)."""
    x = ins["X"]
    rampup = float(attrs.get("rampup_begin_step", 0.0))
    if rampup < 0:
        return {"Out": x}  # dgc_clip_by_norm_op.h:27 disable path
    max_norm = attrs.get("max_norm", 1.0)
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    clipped = jnp.where(norm > max_norm, x * (max_norm / norm), x)
    step = ins["current_step"].reshape(()).astype(jnp.float32)
    active = step >= rampup
    return {"Out": jnp.where(active, clipped, x)}


register_op(
    "dgc_clip_by_norm",
    inputs=[In("X"), In("current_step", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"max_norm": 1.0, "rampup_begin_step": 0.0},
    grad=None,
)(_dgc_clip_by_norm)


@register_op(
    "ema_accumulate",
    inputs=[In("Param", no_grad=True), In("Shadow", no_grad=True),
            In("Decay", dispensable=True, no_grad=True)],
    outputs=[Out("ShadowOut")],
    attrs={"decay": 0.999},
)
def _ema_accumulate(ins, attrs):
    """shadow = decay*shadow + (1-decay)*param (reference
    optimizer.py:3174 ExponentialMovingAverage update block). The
    optional Decay input (a scalar var) overrides the attr — used by the
    thres_steps-adaptive schedule."""
    d = ins.get("Decay")
    if d is None:
        d = attrs.get("decay", 0.999)
    else:
        d = d.reshape(())
    return {"ShadowOut": d * ins["Shadow"] + (1.0 - d) * ins["Param"]}


@register_op(
    "ema_adaptive_decay",
    inputs=[In("ThresSteps", no_grad=True)],
    outputs=[Out("Decay")],
    attrs={"decay": 0.999},
)
def _ema_adaptive_decay(ins, attrs):
    """Step-adaptive EMA decay min(decay, (1+t)/(10+t)) — the reference
    thres_steps warm-up schedule (optimizer.py:3174)."""
    t = ins["ThresSteps"].reshape(()).astype(jnp.float32)
    d = jnp.minimum(jnp.float32(attrs.get("decay", 0.999)),
                    (1.0 + t) / (10.0 + t))
    return {"Decay": d.reshape(1)}


@register_op(
    "lookahead_update",
    inputs=[In("Param", no_grad=True), In("Slow", no_grad=True),
            In("Step", no_grad=True)],
    outputs=[Out("ParamOut"), Out("SlowOut")],
    attrs={"alpha": 0.5, "k": 5},
)
def _lookahead_update(ins, attrs):
    """Every k steps: slow += alpha*(fast-slow); fast = slow (reference
    optimizer.py:4018 Lookahead, functional select instead of cond)."""
    p, slow, step = ins["Param"], ins["Slow"], ins["Step"]
    alpha = attrs.get("alpha", 0.5)
    k = attrs.get("k", 5)
    sync = (step.reshape(()).astype(jnp.int32) % k) == 0
    slow_new = slow + alpha * (p - slow)
    return {"ParamOut": jnp.where(sync, slow_new, p),
            "SlowOut": jnp.where(sync, slow_new, slow)}


@register_op(
    "model_average_accumulate",
    inputs=[In("Param", no_grad=True), In("Sum", no_grad=True),
            In("Count", no_grad=True), In("NumUpdates", no_grad=True)],
    outputs=[Out("SumOut"), Out("CountOut")],
    attrs={"average_window": 0.15, "min_average_window": 10000,
           "max_average_window": 10000},
)
def _model_average_accumulate(ins, attrs):
    """Sliding-window parameter-sum accumulator (reference
    optimizer.py:2870 ModelAverage): when the count would exceed
    min(max_average_window, num_updates * average_window_rate), the
    window restarts at the current parameter value."""
    p, s, c = ins["Param"], ins["Sum"], ins["Count"]
    upd = ins["NumUpdates"].reshape(())
    rate = attrs.get("average_window", 0.15)
    max_w = attrs.get("max_average_window", 10000)
    min_w = attrs.get("min_average_window", 10000)
    # reference average_accumulates_op.h: restart only once the count
    # passes BOTH min_average_window and min(max_window, updates*rate)
    window = jnp.minimum(jnp.float32(max_w), upd * rate)
    c_new = c + 1.0
    cn = c_new.reshape(())
    restart = (cn >= min_w) & (cn >= window)
    sum_out = jnp.where(restart, p, s + p)
    cnt_out = jnp.where(restart, jnp.ones_like(c), c_new)
    return {"SumOut": sum_out, "CountOut": cnt_out}


@register_op(
    "dgc",
    inputs=[In("U", no_grad=True), In("V", no_grad=True),
            In("Grad", no_grad=True), In("CurrentStep", no_grad=True)],
    outputs=[Out("UOut"), Out("VOut"), Out("EncodeGrad"),
             Out("GradOut")],
    attrs={"m": 0.9, "use_nesterov": False, "sparsity": [0.999],
           "rampup_begin_step": 0.0, "rampup_step": 1.0},
    grad=None,
)
def _dgc(ins, attrs):
    """Deep gradient compression (reference dgc_op.h semantics):
    momentum correction (u = m*u + g), velocity accumulation
    (v = v + u), top-k selection by |v|; selected entries emit as the
    (dense-but-mostly-zero) EncodeGrad for the allreduce while local
    u/v zero at selected slots. On TPU the collective stays dense —
    XLA collectives have no sparse wire format — so DGC here preserves
    the ALGORITHM (delayed small-gradient accumulation), not wire
    compression."""
    m = attrs.get("m", 0.9)
    g = ins["Grad"]
    if attrs.get("use_nesterov", False):
        u = m * (ins["U"] + g)  # reference dgc_op.h:138
        v = ins["V"] + u + g
    else:
        u = m * ins["U"] + g
        v = ins["V"] + u
    step = ins["CurrentStep"].reshape(()).astype(jnp.float32)
    sparsity = [float(x) for x in attrs.get("sparsity", [0.999])] or \
        [0.999]
    begin = attrs.get("rampup_begin_step", 0.0)
    period = max(float(attrs.get("rampup_step", 1.0)), 1.0)
    # warm-up schedule (reference dgc_op GetDgcSparsity): walk the
    # sparsity list across the rampup period, then hold the last value
    prog = jnp.clip((step - begin) / period, 0.0, 1.0 - 1e-6)
    idx = (prog * len(sparsity)).astype(jnp.int32)
    s_now = jnp.asarray(sparsity)[idx]
    in_rampup = step < begin
    flat = jnp.abs(v).reshape(-1)
    # dynamic sparsity -> dynamic k is not traceable; use the quantile
    # of |v| as the selection threshold instead of an exact top-k
    thresh = jnp.quantile(flat, s_now)
    mask = (jnp.abs(v) >= thresh) | in_rampup  # no compression pre-rampup
    encoded = jnp.where(mask, v, 0.0)
    return {"UOut": jnp.where(mask, 0.0, u),
            "VOut": jnp.where(mask, 0.0, v),
            "EncodeGrad": encoded,
            "GradOut": encoded}
