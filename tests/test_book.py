"""Classic book-model tests (reference tests/book/): each builds the
reference model shape at small scale on the offline dataset readers,
trains a few dozen steps, and asserts real convergence. These are the
framework's end-to-end truth tests — layers, backward, optimizers,
datasets, and the executor all have to cooperate.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dataset


def _batches(reader, names, batch, dtypes, shapes=None, limit=None):
    """Batch a sample reader into feed dicts (pads ragged int lists)."""
    buf = []
    count = 0
    for sample in reader():
        buf.append(sample)
        if len(buf) == batch:
            feed = {}
            for i, name in enumerate(names):
                col = [s[i] for s in buf]
                arr = np.asarray(col, dtype=dtypes[i])
                if shapes and shapes[i]:
                    arr = arr.reshape((batch,) + tuple(shapes[i]))
                feed[name] = arr
            yield feed
            buf = []
            count += 1
            if limit and count >= limit:
                return


def _train(prog, startup, loss, feeds, scope=None):
    scope = scope or fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        # pin the executor RNG stream so initial weights (and thus the
        # convergence trajectory) don't depend on test order
        exe._core.rng.seed = 90
        exe._core.rng.step = 0
        exe.run(startup)
        for feed in feeds:
            (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    return losses, scope


class TestFitALine:
    """reference book/test_fit_a_line.py: uci_housing linear reg."""

    def test_converges(self):
        B = 20
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.data(name="x", shape=[B, 13], dtype="float32")
            y = fluid.data(name="y", shape=[B, 1], dtype="float32")
            pred = fluid.layers.fc(x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.01).minimize(loss)

        def feeds():
            for _ in range(4):  # epochs over the synthetic table
                yield from _batches(
                    dataset.uci_housing.train(), ["x", "y"], B,
                    ["float32", "float32"], shapes=[None, (1,)],
                    limit=20)

        losses, _ = _train(prog, startup, loss, feeds())
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


class TestWord2Vec:
    """reference book/test_word2vec.py: 4-gram context -> next word."""

    def test_converges(self):
        wd = dataset.imikolov.build_dict()
        V, E, B = len(wd), 16, 32
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            ws = [fluid.data(name="w%d" % i, shape=[B, 1], dtype="int64")
                  for i in range(4)]
            nxt = fluid.data(name="nxt", shape=[B, 1], dtype="int64")
            embs = [fluid.layers.embedding(
                w, size=[V, E],
                param_attr=fluid.ParamAttr(name="shared_emb"))
                for w in ws]
            concat = fluid.layers.concat(embs, axis=-1)
            concat = fluid.layers.reshape(concat, [B, 4 * E])
            hidden = fluid.layers.fc(concat, size=64, act="sigmoid")
            pred = fluid.layers.fc(hidden, size=V, act="softmax")
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, nxt))
            fluid.optimizer.AdamOptimizer(5e-3).minimize(loss)

        def feeds():
            for _ in range(3):
                yield from _batches(
                    dataset.imikolov.train(wd, 5), [f"w{i}" for i in
                                                    range(4)] + ["nxt"],
                    B, ["int64"] * 5, shapes=[(1,)] * 5, limit=30)

        losses, _ = _train(prog, startup, loss, feeds())
        # synthetic Markov text has high entropy, and per-batch
        # difficulty varies by the same ~0.3 nats the 90 steps of
        # learning buy — a last-batch-vs-first-batch check flickers
        # with the init seed (measured 0.83..0.93 around a 0.9 bar).
        # Epoch MEANS cancel the batch mix: ~0.94 for every seed
        # tried, ~1.0 when nothing learns.
        ep = len(losses) // 3
        first, last = np.mean(losses[:ep]), np.mean(losses[-ep:])
        assert last < first * 0.97, (first, last, losses)


class TestRecommenderSystem:
    """reference book/test_recommender_system.py: dual-tower
    embeddings -> cos_sim -> scaled rating regression."""

    def test_converges(self):
        B = 32
        n_users = dataset.movielens.max_user_id() + 1
        n_movies = dataset.movielens.max_movie_id() + 1
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            uid = fluid.data(name="uid", shape=[B, 1], dtype="int64")
            gender = fluid.data(name="gender", shape=[B, 1],
                                dtype="int64")
            age = fluid.data(name="age", shape=[B, 1], dtype="int64")
            job = fluid.data(name="job", shape=[B, 1], dtype="int64")
            mid = fluid.data(name="mid", shape=[B, 1], dtype="int64")
            rating = fluid.data(name="rating", shape=[B, 1],
                                dtype="float32")
            usr = fluid.layers.concat([
                fluid.layers.reshape(fluid.layers.embedding(
                    v, size=[n, 16]), [B, 16])
                for v, n in [(uid, n_users), (gender, 2),
                             (age, len(dataset.movielens.age_table)),
                             (job, dataset.movielens.max_job_id() + 1)]],
                axis=1)
            usr = fluid.layers.fc(usr, size=32, act="relu")
            mov = fluid.layers.reshape(fluid.layers.embedding(
                mid, size=[n_movies, 32]), [B, 32])
            mov = fluid.layers.fc(mov, size=32, act="relu")
            sim = fluid.layers.cos_sim(usr, mov)
            pred = fluid.layers.scale(sim, scale=5.0)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, rating))
            fluid.optimizer.AdamOptimizer(1e-2).minimize(loss)

        def sample_cols(s):
            return (s[0], s[1], s[2], s[3], s[4], s[7])

        def feeds():
            names = ["uid", "gender", "age", "job", "mid", "rating"]
            dts = ["int64"] * 5 + ["float32"]
            buf = []
            for _ in range(3):
                for s in dataset.movielens.train()():
                    buf.append(sample_cols(s))
                    if len(buf) == B:
                        feed = {}
                        for i, n in enumerate(names):
                            feed[n] = np.asarray(
                                [b[i] for b in buf],
                                dtype=dts[i]).reshape(B, 1)
                        yield feed
                        buf = []

        losses, _ = _train(prog, startup, loss, feeds())
        assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


class TestUnderstandSentiment:
    """reference book/notest_understand_sentiment.py: embedding +
    (masked) LSTM over padded tokens -> binary sentiment."""

    T = 16
    B = 32

    def _pad(self, ids):
        out = np.zeros((self.T,), "int64")
        ln = min(len(ids), self.T)
        out[:ln] = ids[:ln]
        return out, ln

    def test_converges(self):
        wd = dataset.imdb.word_dict()
        V, E, H, B, T = len(wd), 16, 32, self.B, self.T
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            toks = fluid.data(name="toks", shape=[B, T], dtype="int64")
            lens = fluid.data(name="lens", shape=[B], dtype="int64")
            lab = fluid.data(name="lab", shape=[B, 1], dtype="int64")
            emb = fluid.layers.embedding(toks, size=[V, E])
            from paddle_tpu.layers.rnn import LSTMCell, rnn as rnn_layer

            cell = LSTMCell(hidden_size=H)
            outs, _ = rnn_layer(cell, emb, sequence_length=lens)
            pooled = fluid.layers.reduce_max(outs, dim=1)
            pred = fluid.layers.fc(pooled, size=2, act="softmax")
            loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, lab))
            fluid.optimizer.AdamOptimizer(5e-3).minimize(loss)

        def feeds():
            buf = []
            for _ in range(3):
                for ids, label in dataset.imdb.train(wd)():
                    buf.append((ids, label))
                    if len(buf) == B:
                        padded = [self._pad(i) for i, _ in buf]
                        yield {
                            "toks": np.stack([p[0] for p in padded]),
                            "lens": np.asarray([p[1] for p in padded],
                                               "int64"),
                            "lab": np.asarray([l for _, l in buf],
                                              "int64").reshape(B, 1),
                        }
                        buf = []

        losses, _ = _train(prog, startup, loss, feeds())
        assert losses[-1] < losses[0] * 0.75, (losses[0], losses[-1])


class TestMachineTranslation:
    """reference book/test_machine_translation.py: seq2seq encoder-
    decoder with teacher forcing, then beam-search generation."""

    B, T, V, E, H, K = 16, 10, 30, 16, 32, 3

    def _pad(self, ids, fill=1):
        out = np.full((self.T,), fill, "int64")
        out[:min(len(ids), self.T)] = ids[:self.T]
        return out

    def test_train_and_beam_decode(self):
        B, T, V, E, H, K = (self.B, self.T, self.V, self.E, self.H,
                            self.K)
        from paddle_tpu.layers.rnn import (
            BeamSearchDecoder, GRUCell, dynamic_decode, rnn as rnn_layer)

        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            src = fluid.data(name="src", shape=[B, T], dtype="int64")
            trg_in = fluid.data(name="trg_in", shape=[B, T],
                                dtype="int64")
            trg_out = fluid.data(name="trg_out", shape=[B, T],
                                 dtype="int64")
            src_emb = fluid.layers.embedding(
                src, size=[V, E], param_attr=fluid.ParamAttr(name="semb"))
            enc_cell = GRUCell(hidden_size=H, name="enc")
            _, enc_final = rnn_layer(enc_cell, src_emb)
            dec_emb = fluid.layers.embedding(
                trg_in, size=[V, E],
                param_attr=fluid.ParamAttr(name="temb"))
            dec_cell = GRUCell(hidden_size=H, name="dec")
            dec_out, _ = rnn_layer(dec_cell, dec_emb,
                                   initial_states=enc_final)
            logits = fluid.layers.fc(
                fluid.layers.reshape(dec_out, [B * T, H]), size=V,
                param_attr=fluid.ParamAttr(name="out_w"),
                bias_attr=False)
            probs = fluid.layers.softmax(logits)
            loss = fluid.layers.mean(fluid.layers.cross_entropy(
                probs, fluid.layers.reshape(trg_out, [B * T, 1])))
            fluid.optimizer.AdamOptimizer(5e-3).minimize(loss)

        reader = dataset.wmt14.train(V)

        def feeds(n_epochs=6):
            buf = []
            for _ in range(n_epochs):
                for s, ti, tn in reader():
                    buf.append((self._pad(s), self._pad(ti),
                                self._pad(tn)))
                    if len(buf) == B:
                        yield {"src": np.stack([b[0] for b in buf]),
                               "trg_in": np.stack([b[1] for b in buf]),
                               "trg_out": np.stack([b[2] for b in buf])}
                        buf = []

        losses, scope = _train(prog, startup, loss, feeds())
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

        # generation program reusing the trained parameters by name
        infer_prog, infer_startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(infer_prog, infer_startup):
            src = fluid.data(name="src", shape=[B, T], dtype="int64")
            src_emb = fluid.layers.embedding(
                src, size=[V, E], param_attr=fluid.ParamAttr(name="semb"))
            enc_cell = GRUCell(hidden_size=H, name="enc2")
            # reuse trained encoder weights via shared names
            enc_cell._proj_attr = fluid.ParamAttr(name="enc_proj_w")
            _, enc_final = rnn_layer(enc_cell, src_emb)
            dec_cell = GRUCell(hidden_size=H, name="dec2")
            emb_fn = lambda ids: fluid.layers.embedding(
                ids, size=[V, E],
                param_attr=fluid.ParamAttr(name="temb"))
            out_fn = lambda x: fluid.layers.fc(
                x, size=V, param_attr=fluid.ParamAttr(name="out_w"),
                bias_attr=False)
            decoder = BeamSearchDecoder(
                dec_cell, start_token=0, end_token=1, beam_size=K,
                embedding_fn=emb_fn, output_fn=out_fn)
            outs, _ = dynamic_decode(decoder, inits=enc_final,
                                     max_step_num=T)
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            # snapshot the TRAINED shared weights: infer_startup must
            # initialize the new decode-path params (enc2/dec2 cells)
            # but would clobber the shared ones (no initialized-skip in
            # initializer ops)
            shared = {}
            for name in list(scope.local_var_names()):
                v = scope.find_var(name)
                if v is not None and v.is_initialized():
                    shared[name] = np.asarray(v.raw().array).copy()
            exe.run(infer_startup)
            feed = next(feeds(1))
            # decode on the CLOBBERED (freshly initialized) weights...
            (ids_fresh,) = exe.run(infer_prog, feed={"src": feed["src"]},
                                   fetch_list=[outs])
            # ...then restore the trained shared weights and decode again
            import jax.numpy as jnp

            for name, val in shared.items():
                scope.var(name).get_tensor().set(jnp.asarray(val))
            (ids,) = exe.run(infer_prog, feed={"src": feed["src"]},
                             fetch_list=[outs])
        ids = np.asarray(ids)
        assert ids.shape == (B, T, K)
        assert ((ids >= 0) & (ids < V)).all()
        # the decode must actually consume the trained weights: if the
        # by-name sharing (or the restore) silently broke, the two
        # decodes would agree
        assert not np.array_equal(ids, np.asarray(ids_fresh))


class TestLabelSemanticRoles:
    """reference book/test_label_semantic_roles.py: embeddings + LSTM
    + linear-chain CRF over conll05."""

    def test_converges(self):
        wd, vd, ld = dataset.conll05.get_dict()
        B, T = 8, 5  # synthetic conll sentences are length 5
        V, NV, NL, E, H = len(wd), len(vd), len(ld), 16, 32
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            words = fluid.data(name="words", shape=[B, T], dtype="int64")
            verb = fluid.data(name="verb", shape=[B, T], dtype="int64")
            mark = fluid.data(name="mark", shape=[B, T], dtype="int64")
            target = fluid.data(name="target", shape=[B, T],
                                dtype="int64")
            feats = fluid.layers.concat([
                fluid.layers.embedding(words, size=[V, E]),
                fluid.layers.embedding(verb, size=[NV, E]),
                fluid.layers.embedding(mark, size=[2, 4]),
            ], axis=-1)
            from paddle_tpu.layers.rnn import LSTMCell, rnn as rnn_layer

            cell = LSTMCell(hidden_size=H)
            outs, _ = rnn_layer(cell, feats)
            emission = fluid.layers.fc(
                fluid.layers.reshape(outs, [B * T, H]), size=NL)
            crf_cost = fluid.layers.linear_chain_crf(
                fluid.layers.reshape(emission, [B, T, NL]), target,
                param_attr=fluid.ParamAttr(name="crfw"))
            loss = fluid.layers.mean(crf_cost)
            fluid.optimizer.SGD(0.05).minimize(loss)

        def feeds():
            buf = []
            for _ in range(6):
                for s in dataset.conll05.test()():
                    buf.append(s)
                    if len(buf) == B:
                        yield {
                            "words": np.asarray([b[0] for b in buf],
                                                "int64"),
                            "verb": np.asarray([b[6] for b in buf],
                                               "int64"),
                            "mark": np.asarray([b[7] for b in buf],
                                               "int64"),
                            "target": np.asarray([b[8] for b in buf],
                                                 "int64"),
                        }
                        buf = []

        losses, _ = _train(prog, startup, loss, feeds())
        assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])
