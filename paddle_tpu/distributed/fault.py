"""Deterministic fault injection at the RPC frame boundary.

The reference hardens its PS dataplane against real networks (gRPC
deadlines + retries, heart_beat_monitor.h liveness); reproducing those
recovery paths requires *causing* the failures on demand, on one host,
deterministically — CI cannot wait for a switch to actually drop a
frame. This module is the shim: ``ps_rpc`` routes every outgoing and
incoming frame through the process-wide injector, which (seeded, so a
failing run replays exactly) drops, delays, duplicates, truncates, or
severs frames according to an env-configured fault plan.

Grammar (``PADDLE_TPU_FAULTS``)::

    plan  := spec[,spec...]
    spec  := <side>.<kind>:<prob>[:<param>]
           | partition:<prob>:<peer>[|<peer>]
    side  := send | recv | any
    kind  := drop | delay | dup | truncate | close | partition
    prob  := float in [0, 1]           (per-frame probability)
    param := delay ms (delay, default 20) | byte count (truncate)
           | endpoint pair (partition — may itself contain colons)

Examples::

    PADDLE_TPU_FAULTS="send.drop:0.05,send.dup:0.05"
    PADDLE_TPU_FAULTS="any.delay:0.2:50,recv.close:0.01"
    PADDLE_TPU_FAULTS="partition:1:127.0.0.1:7001|127.0.0.1:7002"
    PADDLE_TPU_FAULTS="clock_jitter:0.5:600"
    PADDLE_TPU_FAULT_SEED=42

Kinds per side — ``send``: drop (frame never transmitted), delay
(sleep, then transmit), dup (transmit twice — exercises server-side
dedup), truncate (transmit a prefix, then sever — the peer sees EOF
mid-frame), close (sever without transmitting). ``recv``: drop (frame
read and discarded — the reader sees silence), delay, close.

``partition`` (ISSUE 8) is a NETWORK PARTITION between specific
endpoint pairs, not a per-frame coin flip on every socket: the param
names either an endpoint pair ``A|B`` or a single peer endpoint. A
pair rule is active only in processes whose own identity
(``set_identity`` — ``PSServer`` registers its endpoint; env
``PADDLE_TPU_FAULT_IDENTITY`` works too) is one of the pair, and eats
frames (send AND recv) on sockets connected to the OTHER endpoint.
Both partitioned processes run the same plan (the launcher shares the
env), so requests die in A's injector and B's die in B's — the pair
is severed in BOTH directions while every other flow is untouched.
Eaten frames vanish silently (the peer sees timeouts, like a real
partition — never a connection refusal, which the lease/quorum
promotion logic treats as positive evidence of process death).
``prob`` is per frame; 1.0 is a hard partition, below it a flaky
link.

``clock_jitter`` (ISSUE 13) perturbs the PROCESS CLOCK as the lease /
election machinery sees it, not any frame: ``clock_jitter:prob:ms``
gives this process a constant SKEW drawn once (seeded by
``PADDLE_TPU_FAULT_SEED`` x the process fault identity, so every
process in a drill skews differently but reproducibly) in
``[-ms, +ms]``, plus per-event JITTER in the same range with
probability ``prob`` each time a timer is read. ``ps_rpc`` applies the
offset wherever a lease deadline is set or an election timer fires —
the drillable claim is that promotion stays quorum-gated (no
split-brain) even when every participant's clock wanders by up to
±2 lease periods. The skew draw is recorded once in the flight ring
(``fault.clock_skew``); each fired jitter increments
``fault.injected{side=any,kind=clock_jitter}``.

Every injected fault increments ``fault.injected{side=,kind=}`` in the
observability registry (recorded unconditionally, like ``serving.*`` —
fault events are rare and CI asserts on them).
"""
from __future__ import annotations

import hashlib
import os
import random
import socket
import threading
import time
from typing import List, Optional

__all__ = ["FaultRule", "FaultInjector", "FaultInjected",
           "get_injector", "reset_injector", "parse_plan",
           "random_plan", "set_identity", "get_identity",
           "clock_skew"]

_SIDES = ("send", "recv", "any")
_KINDS = ("drop", "delay", "dup", "truncate", "close", "partition",
          "clock_jitter")
_RECV_KINDS = ("drop", "delay", "close", "partition")


class FaultInjected(OSError):
    """Raised by the injector when it severs a connection (close /
    truncate) — an ``OSError`` so transport retry paths treat it
    exactly like a real peer failure."""


class FaultRule:
    __slots__ = ("side", "kind", "prob", "param")

    def __init__(self, side: str, kind: str, prob: float,
                 param=None):
        if side not in _SIDES:
            raise ValueError("fault side must be one of %s, got %r"
                             % (_SIDES, side))
        if kind not in _KINDS:
            raise ValueError("fault kind must be one of %s, got %r"
                             % (_KINDS, kind))
        if side == "recv" and kind not in _RECV_KINDS:
            raise ValueError(
                "recv-side faults support %s (a receiver cannot %s a "
                "frame it does not own)" % (_RECV_KINDS, kind))
        if not 0.0 <= prob <= 1.0:
            raise ValueError("fault probability must be in [0,1], got %r"
                             % prob)
        if kind == "partition":
            if not param or not str(param).strip():
                raise ValueError(
                    "partition rules need a peer endpoint (or an A|B "
                    "pair) as their param")
            param = str(param).strip()
        if kind == "clock_jitter":
            if param is None or float(param) <= 0:
                raise ValueError(
                    "clock_jitter rules need a positive magnitude in "
                    "milliseconds as their param")
            param = float(param)
        self.side = side
        self.kind = kind
        self.prob = prob
        self.param = param

    def partition_peer(self, identity: Optional[str]) -> Optional[str]:
        """The endpoint this rule severs FROM THIS PROCESS, or None
        when the rule is inactive here. A pair ``A|B`` is active only
        when the process identity is one of the pair (the peer is the
        other one); a single-endpoint param partitions this process
        from that peer unconditionally."""
        if self.kind != "partition":
            return None
        if "|" in self.param:
            a, _, b = self.param.partition("|")
            a, b = a.strip(), b.strip()
            if identity == a:
                return b
            if identity == b:
                return a
            return None
        return self.param

    def __repr__(self):
        if self.param is None:
            return "%s.%s:%g" % (self.side, self.kind, self.prob)
        if isinstance(self.param, str):
            return "%s.%s:%g:%s" % (self.side, self.kind, self.prob,
                                    self.param)
        return "%s.%s:%g:%g" % (self.side, self.kind, self.prob,
                                self.param)


def parse_plan(plan: str) -> List[FaultRule]:
    """Parse the ``PADDLE_TPU_FAULTS`` grammar into rules; raises
    ``ValueError`` naming the offending spec."""
    rules = []
    for spec in plan.split(","):
        spec = spec.strip()
        if not spec:
            continue
        try:
            head, _, rest = spec.partition(":")
            side, dot, kind = head.partition(".")
            if not dot and side in ("partition", "clock_jitter"):
                # bare "partition:prob:peer" / "clock_jitter:prob:ms" —
                # side is meaningless for a non-frame fault, default it
                side, kind = "any", side
            if kind == "partition":
                # the param is an endpoint (pair) and endpoints contain
                # colons: only the FIRST colon after prob splits
                prob_s, _, param_s = rest.partition(":")
                prob = float(prob_s)
                param = param_s or None
            else:
                parts = rest.split(":")
                prob = float(parts[0])
                param = float(parts[1]) if len(parts) > 1 else None
            rules.append(FaultRule(side, kind, prob, param))
        except (ValueError, IndexError) as e:
            raise ValueError(
                "bad PADDLE_TPU_FAULTS spec %r (grammar: "
                "side.kind:prob[:param]): %s" % (spec, e)) from None
    return rules


# menu for randomized chaos schedules (tools/chaos_drill.py): only
# RECOVERABLE faults — drop/dup/delay are absorbed by retry + dedup.
# close/truncate sever the connection, which the retry path also
# survives, but at probabilities a drill can afford they would exhaust
# the per-endpoint retry budget and turn a healthy primary into a
# spurious failover (split-brain by chaos harness, not by the system
# under test) — they stay directed-test material.
_RANDOM_MENU = (
    ("send", "drop", (0.01, 0.05), None),
    ("send", "dup", (0.01, 0.05), None),
    ("send", "delay", (0.02, 0.10), (5.0, 30.0)),
    ("recv", "drop", (0.01, 0.04), None),
    ("recv", "delay", (0.02, 0.10), (5.0, 30.0)),
    ("any", "delay", (0.02, 0.08), (5.0, 20.0)),
)


def clock_skew() -> float:
    """The process-wide clock offset (seconds) the lease/election
    timers should apply right now; 0.0 when no injector or no
    ``clock_jitter`` rule is armed. The ONE hook ``ps_rpc`` calls."""
    inj = get_injector()
    if inj is None or not inj.clock_rules:
        return 0.0
    return inj.clock_skew_s()


def random_plan(rng: random.Random, max_rules: int = 3,
                partition_peers=None, clock_jitter_ms=None) -> str:
    """Draw a randomized-but-reproducible ``PADDLE_TPU_FAULTS`` plan
    from the recoverable-fault menu: the same ``rng`` state yields the
    same plan, so a chaos drill's schedule replays from its seed. The
    returned string always round-trips through ``parse_plan``.

    ``partition_peers`` (optional) is a list of ``"A|B"`` endpoint-pair
    strings the plan may sever: when given, the rng picks ONE pair and
    adds a hard ``partition:1`` rule for it (a partition is a recoverable
    fault for the lease/quorum promotion logic the chaos drill gates —
    the partitioned backup must fail its elections, never split the
    brain). Callers that cannot tolerate a severed pair simply don't
    pass peers; the rng consumption without them is unchanged, so
    legacy schedules replay identically.

    ``clock_jitter_ms`` (optional) appends a ``clock_jitter:0.5:<ms>``
    rule AFTER the legacy and partition draws (no extra rng
    consumption — the magnitude is the caller's, typically a fraction
    of the lease in drills and ±2x lease in the directed split-brain
    tests)."""
    n = rng.randint(1, max(1, int(max_rules)))
    picks = rng.sample(range(len(_RANDOM_MENU)), min(n, len(_RANDOM_MENU)))
    specs = []
    for i in sorted(picks):
        side, kind, (plo, phi), prange = _RANDOM_MENU[i]
        prob = round(rng.uniform(plo, phi), 4)
        if prange is None:
            specs.append("%s.%s:%g" % (side, kind, prob))
        else:
            param = round(rng.uniform(*prange), 1)
            specs.append("%s.%s:%g:%g" % (side, kind, prob, param))
    if partition_peers:
        pair = partition_peers[rng.randrange(len(partition_peers))]
        specs.append("partition:1:%s" % pair)
    if clock_jitter_ms:
        specs.append("clock_jitter:0.5:%g" % float(clock_jitter_ms))
    plan = ",".join(specs)
    parse_plan(plan)  # self-check: a generated plan must always parse
    return plan


def _count(side: str, kind: str, **fields) -> None:
    from .. import observability as _obs
    from ..observability import flight as _flight

    _obs.counter("fault.injected", side=side, kind=kind).inc()
    # black-box line: the postmortem of a drill needs WHICH frames the
    # injector ate interleaved with the recovery decisions they caused
    # (partition events carry the severed peer so the drill can prove
    # WHICH pair was cut)
    _flight.record("fault.injected", side=side, kind=kind, **fields)


# -- process identity (partition rules) -------------------------------------

_identity: Optional[str] = None


def set_identity(endpoint: Optional[str]) -> None:
    """Name this process for endpoint-pair partition rules (a PSServer
    registers its own endpoint at construction; env
    ``PADDLE_TPU_FAULT_IDENTITY`` seeds it for anything else)."""
    global _identity
    _identity = endpoint


def get_identity() -> Optional[str]:
    global _identity
    if _identity is None:
        _identity = os.environ.get("PADDLE_TPU_FAULT_IDENTITY") or None
    return _identity


def _peer_endpoint(sock) -> Optional[str]:
    """``host:port`` of the socket's remote end, or None when the
    socket has no peer (fakes in tests, already-severed conns)."""
    try:
        addr = sock.getpeername()
        return "%s:%d" % (addr[0], addr[1])
    except (OSError, AttributeError, TypeError, IndexError):
        return None


class FaultInjector:
    """Seeded per-process fault source. One shared ``random.Random``
    behind a lock: the ROLL SEQUENCE (not per-connection state) is what
    the seed pins, so a run's fault pattern replays given the same
    interleaving — and tests that need exact replay use a single
    thread."""

    def __init__(self, rules: List[FaultRule], seed: int = 0):
        self.rules = [r for r in rules
                      if r.kind not in ("partition", "clock_jitter")]
        self.partitions = [r for r in rules if r.kind == "partition"]
        self.clock_rules = [r for r in rules
                            if r.kind == "clock_jitter"]
        self._seed = int(seed)
        self._rng = random.Random(seed)
        # per-process constant clock skew: drawn lazily (the fault
        # identity may be registered after the injector is built) from
        # seed x identity, so every process in a drill wanders
        # differently but a rerun of the same schedule replays exactly
        self._clock_skew_s: Optional[float] = None
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls) -> Optional["FaultInjector"]:
        plan = os.environ.get("PADDLE_TPU_FAULTS", "")
        if not plan.strip():
            return None
        seed = int(os.environ.get("PADDLE_TPU_FAULT_SEED", "0"))
        return cls(parse_plan(plan), seed=seed)

    def _pick(self, side: str) -> Optional[FaultRule]:
        """At most ONE fault per frame: the first matching rule whose
        roll fires wins (rules are evaluated in plan order). An
        ``any``-side rule whose kind has no recv meaning (dup,
        truncate) only ever applies on the send side."""
        with self._lock:
            for r in self.rules:
                if r.side not in (side, "any"):
                    continue
                if side == "recv" and r.kind not in _RECV_KINDS:
                    continue
                if self._rng.random() < r.prob:
                    return r
        return None

    @staticmethod
    def _sever(sock: socket.socket) -> None:
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _partitioned(self, side: str, sock: socket.socket) -> bool:
        """True when the frame crossing ``sock`` must be blackholed by
        a partition rule: the socket's peer is the severed endpoint and
        the per-frame roll fires. Evaluated BEFORE the single-fault
        menu — a partition overrides everything else on that link."""
        if not self.partitions:
            return False
        peer = _peer_endpoint(sock)
        if peer is None:
            return False
        me = get_identity()
        for r in self.partitions:
            if r.partition_peer(me) != peer:
                continue
            with self._lock:
                fires = self._rng.random() < r.prob
            if fires:
                _count(side, "partition", peer=peer)
                return True
        return False

    # -- clock hook (called by the ps_rpc lease/election machinery) -------

    def clock_skew_s(self) -> float:
        """The offset (seconds) this process's lease/election timers
        are wrong by RIGHT NOW: the per-process constant skew plus,
        with per-rule probability, a fresh jitter draw. 0.0 when no
        ``clock_jitter`` rule is configured."""
        if not self.clock_rules:
            return 0.0
        with self._lock:
            if self._clock_skew_s is None:
                ident = get_identity() or ""
                h = int.from_bytes(
                    hashlib.blake2b(
                        ("%d|%s" % (self._seed, ident)).encode(),
                        digest_size=8).digest(), "big")
                srng = random.Random(h)
                skew = 0.0
                for r in self.clock_rules:
                    skew += srng.uniform(-r.param, r.param) / 1e3
                self._clock_skew_s = skew
                from ..observability import flight as _flight

                _flight.record("fault.clock_skew", identity=ident,
                               skew_ms=round(skew * 1e3, 1))
            off = self._clock_skew_s
            for r in self.clock_rules:
                if self._rng.random() < r.prob:
                    off += self._rng.uniform(-r.param, r.param) / 1e3
                    _count("any", "clock_jitter")
        return off

    # -- frame hooks (called by ps_rpc) -----------------------------------

    def on_send(self, sock: socket.socket, frame: bytes) -> bool:
        """Apply at most one send-side fault to ``frame``. Returns True
        when the frame reached the wire (possibly twice), False when it
        was dropped; raises ``FaultInjected`` when the connection was
        severed."""
        if self._partitioned("send", sock):
            return False  # blackholed: the peer sees silence, not EOF
        r = self._pick("send")
        if r is None:
            sock.sendall(frame)
            return True
        # the flight line names the severed peer: a fleet/PS drill's
        # postmortem needs WHICH link each eaten frame belonged to
        _count("send", r.kind, peer=_peer_endpoint(sock))
        if r.kind == "drop":
            return False
        if r.kind == "delay":
            time.sleep((r.param if r.param is not None else 20.0) / 1e3)
            sock.sendall(frame)
            return True
        if r.kind == "dup":
            sock.sendall(frame)
            sock.sendall(frame)
            return True
        if r.kind == "truncate":
            cut = int(r.param) if r.param is not None else max(
                1, len(frame) // 2)
            sock.sendall(frame[:max(0, min(cut, len(frame) - 1))])
            self._sever(sock)
            raise FaultInjected("injected: frame truncated mid-send")
        # close
        self._sever(sock)
        raise FaultInjected("injected: connection closed before send")

    def on_recv(self, sock: socket.socket) -> str:
        """Decide the fate of the NEXT incoming frame. Returns
        ``"pass"`` (deliver), ``"drop"`` (read and discard), or raises
        ``FaultInjected`` after severing (close)."""
        if self._partitioned("recv", sock):
            return "drop"  # the peer's reply dies in the partition
        r = self._pick("recv")
        if r is None:
            return "pass"
        _count("recv", r.kind, peer=_peer_endpoint(sock))
        if r.kind == "delay":
            time.sleep((r.param if r.param is not None else 20.0) / 1e3)
            return "pass"
        if r.kind == "drop":
            return "drop"
        self._sever(sock)
        raise FaultInjected("injected: connection closed before recv")


# -- process-wide injector (env-armed, resettable for tests) ---------------

_UNSET = object()
_injector = _UNSET
_injector_lock = threading.Lock()


def get_injector() -> Optional[FaultInjector]:
    """The process injector, built from ``PADDLE_TPU_FAULTS`` on first
    use; ``None`` when no plan is configured."""
    global _injector
    if _injector is _UNSET:
        with _injector_lock:
            if _injector is _UNSET:
                _injector = FaultInjector.from_env()
    return _injector


def reset_injector() -> None:
    """Drop the cached injector so the next ``get_injector`` re-reads
    the environment (tests toggle the plan mid-process)."""
    global _injector
    with _injector_lock:
        _injector = _UNSET
