"""Program debugging / visualization utilities.

Parity: /root/reference/python/paddle/fluid/debugger.py
(pprint_program_codes, pprint_block_codes, draw_block_graphviz) and
net_drawer.py — human-readable program text plus graphviz .dot export
(the reference renders via ir/graph_viz_pass.cc; here IrGraph.draw).
"""
from __future__ import annotations

__all__ = ["pprint_program_codes", "pprint_block_codes",
           "draw_block_graphviz"]


def _fmt_attr(v):
    if hasattr(v, "idx"):  # sub-block
        return "block[%d]" % v.idx
    s = repr(v)
    return s if len(s) <= 48 else s[:45] + "..."


def pprint_block_codes(block, show_backward=False):
    """Pseudo-code text for one block (reference debugger.py)."""
    lines = ["// block %d" % block.idx]
    for var in block.vars.values():
        kind = "param" if getattr(var, "trainable", None) is not None \
            and var.persistable else (
                "data" if getattr(var, "is_data", False) else "var")
        lines.append("%s %s : %s%s;" % (
            kind, var.name, getattr(var, "dtype", "?"),
            list(var.shape) if var.shape is not None else "[?]"))
    for op in block.ops:
        if not show_backward and op.type.endswith("_grad"):
            continue
        outs = ", ".join(n for ns in op.outputs.values() for n in ns)
        ins = ", ".join(n for ns in op.inputs.values() for n in ns)
        attrs = ", ".join("%s=%s" % (k, _fmt_attr(v))
                          for k, v in sorted(op.attrs.items())
                          if not k.startswith("_"))
        lines.append("%s = %s(%s)%s;" % (
            outs or "_", op.type, ins,
            " {%s}" % attrs if attrs else ""))
    return "\n".join(lines)


def pprint_program_codes(program, show_backward=False):
    return "\n\n".join(pprint_block_codes(b, show_backward)
                       for b in program.blocks)


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Write a graphviz .dot of the block's op/var graph (reference
    debugger.py:draw_block_graphviz; rendering is `dot -Tpng` as there)."""
    import os

    from .framework import Program
    from .ir import IrGraph

    prog = Program()
    dst = prog.global_block()
    for name, var in block.vars.items():
        v = dst.create_var(name=name, dtype=getattr(var, "dtype", None),
                           persistable=getattr(var, "persistable", False))
        if var.shape is not None:
            v.shape = tuple(var.shape)
    for op in block.ops:
        dst.append_op(op.type, {k: list(v) for k, v in op.inputs.items()},
                      {k: list(v) for k, v in op.outputs.items()},
                      {k: v for k, v in op.attrs.items()
                       if not hasattr(v, "idx")}, infer_shape=False)
    graph = IrGraph(prog)
    d = os.path.dirname(os.path.abspath(path)) or "."
    name = os.path.splitext(os.path.basename(path))[0]
    written = graph.draw(d, name)
    return written
